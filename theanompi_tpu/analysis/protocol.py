"""tpulint protocol model: the cross-process contracts as checkable data.

The survivability plane made this repo a multi-endpoint distributed
system: ``center_server.py``, ``FleetMonServer``, and the statusz
endpoints all speak the §15 wire contract via string-literal op dispatch
on both ends.  Until round 19 those string tables were pinned only by
live socket tests — a deleted handler arm or a drifted retry verdict
surfaced at run time, on the fleet.  This module is the ENGINE-SCOPED
model of those contracts (docs/design.md §21) that the three
``checkers/protocol_conformance.py`` checkers consume:

* :data:`ENDPOINTS` — one :class:`EndpointSpec` per wire endpoint: where
  the server's dispatch function lives, which client surfaces send to
  it, which ops are deliberately idempotent (exempt from the dedup-claim
  requirement), which server attributes own the dedup-guarded state, and
  which handler ops are an external query surface (served for tooling,
  legitimately unsent by in-repo clients).
* **op-table extraction** — :func:`server_op_table` reads the ``op ==
  "push"`` ladders (equality, tuple membership, module-constant ops like
  ``METRICS_OP``) out of a dispatch function; :func:`client_op_table`
  reads the ``{"op": ...}`` literals flowing into the declared request
  functions; :func:`statusz_query_ops` pools every literal
  ``tracing.statusz_query(addr, "<op>")`` call site (the fleetz dialer
  speaks to BOTH statusz-compatible endpoint families).
* **reply/verdict extraction** — :func:`reply_sites` collects each
  handler's reply-header dict literals (plus constant-key subscript
  stores like ``hdr["dedup"] = True``), flagging ``**``-splat/computed
  replies as dynamic; :data:`REPLY_VERDICT_KEYS`/:data:`POLICY_KEYS` and
  :data:`EXCEPTION_VERDICTS` are the §15 close-taxonomy as a table.
* **retry-safety model** — :func:`mutating_methods` computes the
  mutation-summary lattice over a state class (direct ``self.X``
  stores/container mutations, closed over same-class calls);
  :func:`state_aliases` finds the dispatch's local names for the
  server-owned state; :func:`fold_op_test` decides a dispatch ``if``
  test for one op value so the checker can walk exactly that op's
  handler slice.
* **membership state machine** — :data:`STATUS_EVENTS` maps each status
  value a controller method may write to the event it must emit,
  :data:`EVENT_HOOKS`/:data:`REACTOR_HOOKS` pin the reactor fan-out
  vocabulary, and :data:`HEADER_FIELDS` declares the wire-header field
  vocabulary per protocol version (the v1→v2 ``trace`` precedent made
  checkable: a new header field must be declared here with its version,
  and v2-OPTIONAL fields may only be read with ``.get`` — a subscript
  read would KeyError against a v1 peer).

Everything here is static (stdlib ``ast`` over the shared
:class:`~.engine.ProgramIndex`) and jax-free.  Extraction that cannot
resolve something returns nothing rather than guessing — partial trees
(precommit staged-blob runs) skip cross-file checks they cannot see,
never invent findings; :class:`EndpointSpec.requires` lists the files a
direction needs in scope before it may claim an op is unsent.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .engine import FuncRecord, ProgramIndex, body_walk

# -- endpoint files (repo-relative; fixtures mirror these paths) -------------

CENTER_PATH = "theanompi_tpu/parallel/center_server.py"
FLEETMON_PATH = "theanompi_tpu/utils/fleetmon.py"
TRACING_PATH = "theanompi_tpu/utils/tracing.py"
WIRE_PATH = "theanompi_tpu/parallel/wire.py"
MEMBERSHIP_PATH = "theanompi_tpu/parallel/membership.py"
ASYNC_EASGD_PATH = "theanompi_tpu/parallel/async_easgd.py"
FLEETZ_PATH = "scripts/fleetz.py"

#: the one generic statusz dialer — its literal op args pool into the
#: statusz-compatible endpoint family's client table
STATUSZ_QUERY_FN = "theanompi_tpu.utils.tracing.statusz_query"
DEFAULT_STATUSZ_OP = "health"      # statusz_query's own default op


# -- declarations ------------------------------------------------------------

@dataclass(frozen=True)
class ClientSurface:
    """One place requests originate: calls to ``request_fns`` within
    ``scope`` (a class or function simple name; "" = whole module) of
    ``path``, whose header dict (positional ``header_arg``) carries the
    op literal."""

    path: str
    scope: str
    request_fns: Tuple[str, ...]
    header_arg: int = 0


@dataclass(frozen=True)
class ReadSurface:
    """Where a client stack reads reply headers: names bound from
    ``request_fns`` call results (first element of a tuple unpack when
    ``tuple_result``, the whole result otherwise) scanned for
    ``.get("k")`` / ``["k"]`` reads."""

    path: str
    scope: str
    request_fns: Tuple[str, ...]
    tuple_result: bool = True


@dataclass(frozen=True)
class EndpointSpec:
    name: str
    server_path: str
    dispatch: str                       # dotted suffix: "Handler._dispatch"
    clients: Tuple[ClientSurface, ...] = ()
    reads: Tuple[ReadSurface, ...] = ()
    #: handler ops that are an external query surface (CLI tooling,
    #: Prometheus scrapes, tests) — legitimately unsent by in-repo
    #: clients.  Everything else unsent is a dead handler.
    external_ops: FrozenSet[str] = frozenset()
    #: mutating ops exempt from the dedup-claim requirement because the
    #: mutation is idempotent BY ALGEBRA (seed-once init, set-membership
    #: demote/readmit) — the §21 suppression surface for checker (b).
    idempotent_ops: FrozenSet[str] = frozenset()
    #: server attrs holding the dedup-guarded state (``self.center``)
    state_attrs: Tuple[str, ...] = ()
    #: dotted classes owning that state — their mutating methods are
    #: what a handler path must not reach unclaimed
    state_classes: Tuple[str, ...] = ()
    #: server attrs holding the DedupWindow (claim machinery, exempt)
    dedup_attrs: Tuple[str, ...] = ("dedup",)
    #: member of the statusz-dial family (fleetz speaks to all of them
    #: with one query function, so their client table is pooled)
    statusz_compat: bool = False
    #: served behind WireClient — the shared verdict vocabulary applies
    wire_verdicts: bool = False
    #: files that must be in scope before the unsent-handler/verdict
    #: directions may fire (partial trees skip, never invent)
    requires: Tuple[str, ...] = ()


ENDPOINTS: Tuple[EndpointSpec, ...] = (
    EndpointSpec(
        name="center",
        server_path=CENTER_PATH,
        dispatch="Handler._dispatch",
        clients=(ClientSurface(CENTER_PATH, "RemoteCenter",
                               ("_roundtrip",)),),
        reads=(ReadSurface(CENTER_PATH, "RemoteCenter", ("_roundtrip",)),),
        external_ops=frozenset(),
        # init seeds once (ensure_init_leaves is a no-op when leaves
        # exist); demote/readmit are set membership — retrying any of
        # them re-applies the same state
        idempotent_ops=frozenset({"init", "demote", "readmit"}),
        state_attrs=("center",),
        state_classes=("theanompi_tpu.parallel.async_easgd.ElasticCenter",),
        wire_verdicts=True,
    ),
    EndpointSpec(
        name="fleetmon",
        server_path=FLEETMON_PATH,
        dispatch="Handler._dispatch",
        clients=(ClientSurface(FLEETMON_PATH, "MetricStreamer",
                               ("request",)),),
        # series/rollup/exposition are the ops query surface (fleetz
        # --watch dials health/alerts/events; Prometheus scrapes ride
        # exposition externally; tests drive series/rollup directly)
        external_ops=frozenset({"series", "rollup", "exposition"}),
        idempotent_ops=frozenset(),
        state_attrs=("collector",),
        state_classes=("theanompi_tpu.utils.fleetmon.FleetCollector",),
        statusz_compat=True,
        wire_verdicts=True,
        requires=(TRACING_PATH, FLEETZ_PATH),
    ),
    EndpointSpec(
        name="statusz",
        server_path=TRACING_PATH,
        dispatch="Handler.handle",
        statusz_compat=True,
        requires=(FLEETMON_PATH, FLEETZ_PATH),
    ),
)

#: the shared wire-client verdict reads (every wire endpoint's replies
#: are interpreted here)
WIRE_CLIENT_READS = ReadSurface(WIRE_PATH, "WireClient",
                                ("recv_msg", "_request_locked"))

#: reply-header keys that GATE client behavior (§15): ``retry`` = re-send
#: the same token; ``busy`` = an in-flight twin's retryable non-ack;
#: ``uninit`` = structured terminal (client re-seeds); ``dedup`` = the
#: applied-before marker trace assembly reads.
POLICY_KEYS = ("retry", "busy", "uninit", "dedup")
#: the full verdict vocabulary a reply header may carry
REPLY_VERDICT_KEYS = ("ok", "error", "srv") + POLICY_KEYS

#: §15 close-taxonomy, checkable: the reply a server sends from these
#: exception handlers must be retryable / terminal as declared.
EXCEPTION_VERDICTS = {
    "CorruptPayload": "retryable",      # bytes bad, stream aligned
    "VersionMismatch": "terminal",      # never retried, loud
}

#: wire-header field vocabulary: field -> (protocol version introduced,
#: subscript-read allowed).  v2 OPTIONAL fields (absent ⇒ v1 behavior)
#: must be read with ``.get`` — ``header["trace"]`` would KeyError
#: against a v1 peer.  An undeclared read fails the gate: a new header
#: field must land here WITH its version, which is exactly the v1→v2
#: ``trace`` precedent as a standing rule.
HEADER_FIELDS = {
    "op": (1, True), "tok": (1, True), "crc": (1, True), "v": (1, True),
    "island": (1, True), "rank": (1, True), "role": (1, True),
    "status": (1, True), "series": (1, True), "n": (1, True),
    "reason": (1, True),
    "trace": (2, False), "srv": (2, False),
}

# -- membership state machine ------------------------------------------------

CONTROLLER_CLASS = ("theanompi_tpu.parallel.membership",
                    "MembershipController")
REACTOR_ROOT = "theanompi_tpu.parallel.membership.Reactor"
MEMBERSHIP_VOCAB = "theanompi_tpu.parallel.membership.MEMBERSHIP_EVENTS"
CENTER_VOCAB = "theanompi_tpu.parallel.membership.CENTER_EVENTS"
ACTIONS_VOCAB = "theanompi_tpu.utils.fleetmon.RULE_ACTIONS"

#: status value a controller method writes -> the event that write must
#: emit (the live⇄demoted→dead/left machine, docs/design.md §14)
STATUS_EVENTS = {
    "live": "worker_join",
    "demoted": "worker_demote",
    "dead": "worker_leave",
    "left": "worker_leave",
}
#: event -> reactor hooks ``_emit`` may legally fan it out through
EVENT_HOOKS = {
    "worker_join": ("on_join", "on_readmit"),
    "worker_leave": ("on_leave",),
    "worker_demote": ("on_demote",),
}
#: the full reactor hook vocabulary every Reactor subclass must handle
#: or explicitly ignore (an override with ``pass``)
REACTOR_HOOKS = ("on_join", "on_leave", "on_demote", "on_readmit")

#: where alert ACTIONS are handled — every fleetmon.RULE_ACTIONS entry
#: must be dispatched in one of these (module-path, dotted suffix) fns
ACTION_HANDLERS = (
    (FLEETMON_PATH, "apply_alert"),
    (MEMBERSHIP_PATH, "ElasticSupervisor._tick_fleetmon"),
)

#: container methods that mutate their receiver (the mutation-summary
#: lattice's leaf rule next to plain ``self.X = ...`` stores)
CONTAINER_MUTATORS = frozenset({
    "append", "appendleft", "add", "discard", "remove", "update", "pop",
    "popitem", "setdefault", "extend", "insert", "clear",
})


# -- small shared helpers ----------------------------------------------------

def module_of(path: str) -> str:
    return path[:-3].replace("/", ".") if path.endswith(".py") else \
        path.replace("/", ".")


@dataclass
class OpSite:
    """One place an op string appears (a dispatch comparison or a client
    send)."""

    path: str
    node: ast.AST

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 1)

    @property
    def col(self) -> int:
        return getattr(self.node, "col_offset", 0)


def const_str(node: ast.AST, sf, index: ProgramIndex) -> Optional[str]:
    """A statically-known string: literal, imported module constant, or
    a constant of the SAME module (``METRICS_OP`` compared in its own
    file) — None when not evaluable (never guessed)."""
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, str) else None
    if isinstance(node, (ast.Name, ast.Attribute)):
        resolved = sf.resolver.resolve(node)
        if resolved is None and isinstance(node, ast.Name):
            resolved = f"{sf.resolver.module}.{node.id}"
        if resolved:
            v = index.module_constant(resolved)
            if isinstance(v, str):
                return v
    return None


def dispatch_record(index: ProgramIndex,
                    spec: EndpointSpec) -> Optional[FuncRecord]:
    """The server's dispatch FuncRecord, or None when the file is in
    scope but the declared function is not (model out of date — the
    wire-contract checker reports that loudly)."""
    qn = f"{module_of(spec.server_path)}.{spec.dispatch}"
    recs = [r for r in index.by_qualname.get(qn, [])
            if r.sf.path == spec.server_path]
    return recs[0] if recs else None


def op_var_names(fn_node: ast.AST) -> Set[str]:
    """Names assigned from ``<header>.get("op")`` — the dispatch's op
    variable(s)."""
    out: Set[str] = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign) and \
                isinstance(sub.value, ast.Call) and \
                isinstance(sub.value.func, ast.Attribute) and \
                sub.value.func.attr == "get" and sub.value.args and \
                isinstance(sub.value.args[0], ast.Constant) and \
                sub.value.args[0].value == "op":
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _comparison_ops(test: ast.AST, opvars: Set[str], sf,
                    index: ProgramIndex) -> List[Tuple[str, ast.AST]]:
    """(op value, comparison node) for every equality/membership test of
    an op variable inside ``test``."""
    out: List[Tuple[str, ast.AST]] = []
    for sub in ast.walk(test):
        if not isinstance(sub, ast.Compare) or \
                not isinstance(sub.left, ast.Name) or \
                sub.left.id not in opvars:
            continue
        for cmp_op, comp in zip(sub.ops, sub.comparators):
            if isinstance(cmp_op, (ast.Eq, ast.NotEq)):
                v = const_str(comp, sf, index)
                if v is not None:
                    out.append((v, sub))
            elif isinstance(cmp_op, (ast.In, ast.NotIn)) and \
                    isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                for e in comp.elts:
                    v = const_str(e, sf, index)
                    if v is not None:
                        out.append((v, sub))
    return out


# -- server/client op tables -------------------------------------------------

def server_op_table(index: ProgramIndex, spec: EndpointSpec
                    ) -> Optional[Dict[str, OpSite]]:
    """Every op the dispatch function compares its op variable against
    (first comparison site per op), or None when the dispatch function
    is missing from an in-scope server file."""
    rec = dispatch_record(index, spec)
    if rec is None:
        return None
    opvars = op_var_names(rec.node)
    if not opvars:
        # handle() styles that take `op` as a parameter
        opvars = {p for p in rec.params() if p == "op"}
    table: Dict[str, OpSite] = {}
    for sub in ast.walk(rec.node):
        test = None
        if isinstance(sub, (ast.If, ast.IfExp, ast.While)):
            test = sub.test
        elif isinstance(sub, ast.Compare):
            test = sub
        if test is None:
            continue
        for v, node in _comparison_ops(test, opvars, rec.sf, index):
            table.setdefault(v, OpSite(rec.sf.path, node))
    return table


def _scope_records(index: ProgramIndex, path: str,
                   scope: str) -> List[FuncRecord]:
    sf = index.by_path.get(path)
    if sf is None:
        return []
    module = sf.resolver.module
    out: List[FuncRecord] = []
    for rec in index.records.values():
        if rec.sf.path != path:
            continue
        if not scope:
            out.append(rec)
        elif rec.class_key == (module, scope) or \
                rec.qualname == f"{module}.{scope}" or \
                rec.qualname.startswith(f"{module}.{scope}."):
            out.append(rec)
    return out


def _local_dict(fn_node: ast.AST, name: str) -> Optional[ast.Dict]:
    """The dict literal a local name was assigned from (the
    ``header = {"op": ...}; client.request(header, ...)`` shape)."""
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign) and \
                isinstance(sub.value, ast.Dict) and \
                any(isinstance(t, ast.Name) and t.id == name
                    for t in sub.targets):
            return sub.value
    return None


def _dict_key_value(d: ast.Dict, key: str) -> Optional[ast.AST]:
    for k, v in zip(d.keys, d.values):
        if isinstance(k, ast.Constant) and k.value == key:
            return v
    return None


def client_op_table(index: ProgramIndex, spec: EndpointSpec
                    ) -> Dict[str, List[OpSite]]:
    """Ops the declared client surfaces send: the ``"op"`` values of
    header dict literals (inline or bound to a local name) passed to the
    surface's request functions."""
    out: Dict[str, List[OpSite]] = {}
    for surf in spec.clients:
        for rec in _scope_records(index, surf.path, surf.scope):
            if isinstance(rec.node, ast.Lambda):
                continue
            for sub in ast.walk(rec.node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if fname not in surf.request_fns:
                    continue
                hdr = sub.args[surf.header_arg] \
                    if len(sub.args) > surf.header_arg else None
                for kw in sub.keywords:
                    if kw.arg == "header":
                        hdr = kw.value
                if isinstance(hdr, ast.Name):
                    hdr = _local_dict(rec.node, hdr.id)
                if not isinstance(hdr, ast.Dict):
                    continue
                v = _dict_key_value(hdr, "op")
                op = const_str(v, rec.sf, index) if v is not None else None
                if op is not None:
                    out.setdefault(op, []).append(
                        OpSite(rec.sf.path, sub))
    return out


def statusz_query_ops(index: ProgramIndex) -> Dict[str, List[OpSite]]:
    """Every literal op sent through ``tracing.statusz_query`` in
    non-test files (tests deliberately send unknown ops to probe the
    error path).  A call with the op omitted sends the function's own
    default (``health``)."""
    out: Dict[str, List[OpSite]] = {}
    for sf in index.files:
        if sf.path.startswith("tests/"):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if sf.resolver.resolve(node.func) != STATUSZ_QUERY_FN:
                continue
            arg = node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "op":
                    arg = kw.value
            if arg is None:
                out.setdefault(DEFAULT_STATUSZ_OP, []).append(
                    OpSite(sf.path, node))
                continue
            v = const_str(arg, sf, index)
            if v is not None:
                out.setdefault(v, []).append(OpSite(sf.path, node))
    return out


# -- reply sites -------------------------------------------------------------

@dataclass
class ReplySite:
    path: str
    node: ast.AST
    keys: Optional[FrozenSet[str]]      # None = dynamic (splat/computed)
    consts: Dict[str, object] = field(default_factory=dict)

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 1)


def _server_class_records(index: ProgramIndex,
                          spec: EndpointSpec) -> List[FuncRecord]:
    """Every method of the dispatch function's class (handle +
    _dispatch + anything else on the Handler) — the scope reply/verdict
    extraction covers."""
    rec = dispatch_record(index, spec)
    if rec is None:
        return []
    if rec.class_key is None:
        return [rec]
    return [r for r in index.records.values()
            if r.class_key == rec.class_key and
            not isinstance(r.node, ast.Lambda)]


def _reply_header_arg(call: ast.Call) -> Optional[ast.AST]:
    """The header expression of a reply site — a call to the local
    ``reply(...)`` closure or to ``<x>.send_msg(sock, hdr)`` — or None
    when the call is neither."""
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id == "reply":
        return call.args[0] if call.args else None
    if isinstance(fn, ast.Attribute) and fn.attr == "send_msg":
        return call.args[1] if len(call.args) > 1 else None
    return None


def reply_sites(index: ProgramIndex, spec: EndpointSpec
                ) -> Tuple[List[ReplySite], Set[str]]:
    """(reply sites, extra emitted keys).  The extra keys are
    constant-key subscript stores (``hdr["dedup"] = True``) into names
    that FLOW INTO a reply somewhere in the handler class — reply
    headers are sometimes built up before the send, but an unrelated
    local dict's keys must not launder into the emitted set (they would
    mask unset-reply-field findings)."""
    sites: List[ReplySite] = []
    extra: Set[str] = set()
    recs = _server_class_records(index, spec)
    # names that reach a reply header argument (``reply(hdr, ...)``,
    # ``send_msg(sock, h)``) anywhere in the class, PLUS the reply
    # closure's own parameter names (``def reply(hdr, ...)`` — its body
    # builds ``h = dict(hdr)`` and sends h) and names assigned from them
    header_names: Set[str] = set()
    for rec in recs:
        for sub in ast.walk(rec.node):
            if isinstance(sub, ast.Call):
                hdr = _reply_header_arg(sub)
                if isinstance(hdr, ast.Name):
                    header_names.add(hdr.id)
            elif isinstance(sub, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)) and \
                    sub.name == "reply":
                header_names.update(a.arg for a in sub.args.args)
    # one transitive hop: `h = dict(hdr)` / `h = hdr` style rebinds
    for rec in recs:
        for sub in ast.walk(rec.node):
            if isinstance(sub, ast.Assign) and any(
                    isinstance(n, ast.Name) and n.id in header_names
                    for n in ast.walk(sub.value)):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        header_names.add(t.id)
    for rec in recs:
        for sub in ast.walk(rec.node):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in header_names and \
                            isinstance(t.slice, ast.Constant) and \
                            isinstance(t.slice.value, str):
                        extra.add(t.slice.value)
                continue
            if not isinstance(sub, ast.Call):
                continue
            hdr = _reply_header_arg(sub)
            if hdr is None:
                continue
            if isinstance(hdr, ast.Dict):
                keys = frozenset(k.value for k in hdr.keys
                                 if isinstance(k, ast.Constant))
                if any(k is None for k in hdr.keys):   # ** splat
                    keys = None
                consts = {}
                for k, v in zip(hdr.keys, hdr.values):
                    if isinstance(k, ast.Constant) and \
                            isinstance(v, ast.Constant):
                        consts[k.value] = v.value
                sites.append(ReplySite(rec.sf.path, sub, keys, consts))
            else:
                sites.append(ReplySite(rec.sf.path, sub, None))
    return sites, extra


def exception_reply_sites(index: ProgramIndex, spec: EndpointSpec,
                          exc_name: str) -> List[ReplySite]:
    """Reply sites INSIDE ``except <...>.{exc_name}`` handlers of the
    server class — the close-taxonomy check's input."""
    out: List[ReplySite] = []
    for rec in _server_class_records(index, spec):
        for sub in ast.walk(rec.node):
            if not isinstance(sub, ast.ExceptHandler) or sub.type is None:
                continue
            types = sub.type.elts if isinstance(sub.type, ast.Tuple) \
                else [sub.type]
            match = False
            for t in types:
                dotted = None
                if isinstance(t, ast.Name):
                    dotted = t.id
                elif isinstance(t, ast.Attribute):
                    dotted = t.attr
                if dotted == exc_name:
                    match = True
            if not match:
                continue
            handler_mod = ast.Module(body=sub.body, type_ignores=[])
            for call in ast.walk(handler_mod):
                if not isinstance(call, ast.Call):
                    continue
                hdr = _reply_header_arg(call)
                if isinstance(hdr, ast.Dict):
                    keys = frozenset(k.value for k in hdr.keys
                                     if isinstance(k, ast.Constant))
                    consts = {k.value: v.value
                              for k, v in zip(hdr.keys, hdr.values)
                              if isinstance(k, ast.Constant)
                              and isinstance(v, ast.Constant)}
                    out.append(ReplySite(rec.sf.path, call, keys, consts))
    return out


# -- client reply reads ------------------------------------------------------

def reply_reads(index: ProgramIndex,
                surf: ReadSurface) -> Dict[str, OpSite]:
    """Reply-header keys the surface reads: names bound from its request
    functions' results, scanned for ``.get("k")`` / ``["k"]``."""
    out: Dict[str, OpSite] = {}
    for rec in _scope_records(index, surf.path, surf.scope):
        if isinstance(rec.node, ast.Lambda):
            continue
        reply_vars: Set[str] = set()
        for sub in ast.walk(rec.node):
            if not isinstance(sub, ast.Assign) or \
                    not isinstance(sub.value, ast.Call):
                continue
            fn = sub.value.func
            fname = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if fname not in surf.request_fns:
                continue
            for t in sub.targets:
                if surf.tuple_result and isinstance(t, ast.Tuple) and \
                        t.elts and isinstance(t.elts[0], ast.Name):
                    reply_vars.add(t.elts[0].id)
                elif not surf.tuple_result and isinstance(t, ast.Name):
                    reply_vars.add(t.id)
        if not reply_vars:
            continue
        for sub in ast.walk(rec.node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "get" and \
                    isinstance(sub.func.value, ast.Name) and \
                    sub.func.value.id in reply_vars and sub.args and \
                    isinstance(sub.args[0], ast.Constant) and \
                    isinstance(sub.args[0].value, str):
                out.setdefault(sub.args[0].value,
                               OpSite(rec.sf.path, sub))
            elif isinstance(sub, ast.Subscript) and \
                    isinstance(sub.value, ast.Name) and \
                    sub.value.id in reply_vars and \
                    isinstance(sub.ctx, ast.Load) and \
                    isinstance(sub.slice, ast.Constant) and \
                    isinstance(sub.slice.value, str):
                out.setdefault(sub.slice.value,
                               OpSite(rec.sf.path, sub))
    return out


# -- header-field reads ------------------------------------------------------

@dataclass
class HeaderRead:
    path: str
    node: ast.AST
    fieldname: str
    subscript: bool

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 1)


def header_reads(index: ProgramIndex,
                 spec: EndpointSpec) -> List[HeaderRead]:
    """Request-header fields the dispatch function reads — through the
    ``header`` parameter or names unpacked from ``recv_msg``."""
    rec = dispatch_record(index, spec)
    if rec is None:
        return []
    hdr_vars: Set[str] = {p for p in rec.params() if p == "header"}
    for sub in ast.walk(rec.node):
        if isinstance(sub, ast.Assign) and \
                isinstance(sub.value, ast.Call) and \
                isinstance(sub.value.func, (ast.Name, ast.Attribute)):
            fn = sub.value.func
            fname = fn.id if isinstance(fn, ast.Name) else fn.attr
            if fname == "recv_msg":
                for t in sub.targets:
                    if isinstance(t, ast.Tuple) and t.elts and \
                            isinstance(t.elts[0], ast.Name):
                        hdr_vars.add(t.elts[0].id)
    out: List[HeaderRead] = []
    for sub in ast.walk(rec.node):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr == "get" and \
                isinstance(sub.func.value, ast.Name) and \
                sub.func.value.id in hdr_vars and sub.args and \
                isinstance(sub.args[0], ast.Constant) and \
                isinstance(sub.args[0].value, str):
            out.append(HeaderRead(rec.sf.path, sub, sub.args[0].value,
                                  False))
        elif isinstance(sub, ast.Subscript) and \
                isinstance(sub.value, ast.Name) and \
                sub.value.id in hdr_vars and \
                isinstance(sub.ctx, ast.Load) and \
                isinstance(sub.slice, ast.Constant) and \
                isinstance(sub.slice.value, str):
            out.append(HeaderRead(rec.sf.path, sub, sub.slice.value,
                                  True))
    return out


# -- retry-safety model ------------------------------------------------------

def _attr_root(node: ast.AST) -> Tuple[Optional[str], List[str]]:
    """(root Name id, attribute chain) of ``name.a.b`` — (None, []) for
    anything not rooted at a plain Name."""
    chain: List[str] = []
    while isinstance(node, ast.Attribute):
        chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return node.id, list(reversed(chain))
    return None, []


def _direct_self_mutation(rec: FuncRecord) -> bool:
    """Does this method body store into ``self.X`` (assign/augassign/
    del/subscript) or call a container mutator on a ``self`` attr?"""
    for sub in body_walk(rec.node):
        targets: List[ast.AST] = []
        if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = sub.targets if isinstance(sub, ast.Assign) \
                else [sub.target]
        elif isinstance(sub, ast.Delete):
            targets = list(sub.targets)
        for t in targets:
            if isinstance(t, ast.Subscript):
                t = t.value
            root, chain = _attr_root(t)
            if root == "self" and chain:
                return True
        if isinstance(sub, ast.Call):
            root, chain = _attr_root(sub.func)
            if root == "self" and len(chain) >= 2 and \
                    chain[-1] in CONTAINER_MUTATORS:
                return True
    return False


def mutating_methods(index: ProgramIndex,
                     dotted_classes: Sequence[str]) -> Set[str]:
    """Method names of the state classes (and their in-scope subclasses)
    that mutate ``self`` — directly, or by calling a same-class mutating
    method (monotone fixpoint: the §21 mutation-summary lattice)."""
    keys: Set[Tuple[str, str]] = set()
    for dotted in dotted_classes:
        key = index._class_keys.get(dotted)
        if key is None:
            continue
        keys.add(key)
        keys |= index._subclasses.get(key, set())
    if not keys:
        return set()
    recs = [r for r in index.records.values()
            if r.class_key in keys and not isinstance(r.node, ast.Lambda)]
    mut = {r.name for r in recs if _direct_self_mutation(r)}
    changed = True
    while changed:
        changed = False
        for r in recs:
            if r.name in mut:
                continue
            for sub in body_walk(r.node):
                if isinstance(sub, ast.Call):
                    root, chain = _attr_root(sub.func)
                    if root == "self" and len(chain) == 1 and \
                            chain[0] in mut:
                        mut.add(r.name)
                        changed = True
                        break
    return mut


def self_aliases(index: ProgramIndex, spec: EndpointSpec) -> Set[str]:
    """Local names the server file binds to a bare ``self`` (the
    ``outer = self`` closure-capture idiom) — derived, not hardcoded, so
    renaming the capture cannot silently blind the mutation scan."""
    out: Set[str] = {"self"}
    sf = index.by_path.get(spec.server_path)
    if sf is None:
        return out
    for sub in ast.walk(sf.tree):
        if isinstance(sub, ast.Assign) and \
                isinstance(sub.value, ast.Name) and \
                sub.value.id in ("self",):
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def state_aliases(index: ProgramIndex, spec: EndpointSpec,
                  attrs: Sequence[str]) -> Set[str]:
    """Local names the server file binds to ``<self>.<attr>`` for the
    declared state attrs, through ``self`` or any of its captures
    (:func:`self_aliases`) — the dispatch closure's handles on the
    server-owned state (``center = self.center`` in ``start()``).
    File-level and deliberately coarse: an extra alias can only widen
    the mutation scan, never hide one."""
    sf = index.by_path.get(spec.server_path)
    if sf is None:
        return set()
    selves = self_aliases(index, spec)
    out: Set[str] = set(attrs)
    for sub in ast.walk(sf.tree):
        if not isinstance(sub, ast.Assign):
            continue
        root, chain = _attr_root(sub.value)
        if root in selves and len(chain) == 1 and chain[0] in attrs:
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def fold_op_test(test: ast.AST, opvars: Set[str], value: str, sf,
                 index: ProgramIndex) -> Optional[bool]:
    """Decide a dispatch ``if`` test for one op value: True/False when
    the test is a pure function of the op variable, None otherwise
    (both arms possible)."""
    if isinstance(test, ast.BoolOp):
        parts = [fold_op_test(v, opvars, value, sf, index)
                 for v in test.values]
        if isinstance(test.op, ast.And):
            if any(p is False for p in parts):
                return False
            if all(p is True for p in parts):
                return True
            return None
        if any(p is True for p in parts):
            return True
        if all(p is False for p in parts):
            return False
        return None
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = fold_op_test(test.operand, opvars, value, sf, index)
        return None if inner is None else not inner
    if isinstance(test, ast.Compare) and \
            isinstance(test.left, ast.Name) and \
            test.left.id in opvars and len(test.ops) == 1:
        cmp_op, comp = test.ops[0], test.comparators[0]
        if isinstance(cmp_op, (ast.Eq, ast.NotEq)):
            v = const_str(comp, sf, index)
            if v is None:
                return None
            eq = (v == value)
            return eq if isinstance(cmp_op, ast.Eq) else not eq
        if isinstance(cmp_op, (ast.In, ast.NotIn)) and \
                isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
            vals = [const_str(e, sf, index) for e in comp.elts]
            if any(v is None for v in vals):
                return None
            member = value in vals
            return member if isinstance(cmp_op, ast.In) else not member
    return None


def block_terminates(stmts: Sequence[ast.stmt]) -> bool:
    """Every path through this block exits it (return/raise/continue/
    break, or an if whose arms both terminate)."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
        return True
    if isinstance(last, ast.If):
        return block_terminates(last.body) and \
            block_terminates(last.orelse)
    return False
