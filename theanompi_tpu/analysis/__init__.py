"""tpulint — whole-program invariant checkers for the framework's hot
paths.

docs/design.md §6 promises the invariants are machine-checked; §12 lists
the ones a static pass can hold: tracing safety inside fused ``lax.scan``
bodies, ``jax.random`` key discipline, and donation rules around the AOT
cache — each closed over the repo-wide call graph
(``analysis/engine.py``) — plus SPMD collective discipline (axis-name
validity, rank-divergent branches, async start/done pairing),
PartitionSpec/shard_map schema checks, ``exchange_body`` collective
symmetry, the ``jax_compat`` shim boundary, the one-attribute-check
telemetry hot-path contract, and the telemetry/recorder schema sync.
Each is a :class:`~.core.Checker` registered here; ``scripts/lint.py``
is the CLI and ``scripts/tier1.sh`` runs it (``--check-baseline``)
before pytest, so a host-side leak into a compiled hot path fails the
gate in seconds (sub-second on a ``.tpulint_cache/`` hit) instead of
surfacing as a silent throughput regression after a 270-second TPU
compile.

The package is stdlib-only (plus numpy transitively via the schema-drift
checker's live probe) and deliberately importable WITHOUT jax:
``scripts/lint.py`` bootstraps it under a synthetic parent package so
the repo-wide walk never drags a backend in.

Suppression: append ``# tpulint: disable=<check>[,<check>...]`` to the
flagged line (or put it on its own line directly above).  Grandfathered
findings live in ``tpulint_baseline.json`` (one justification per entry,
regenerated deterministically by ``scripts/lint.py --update-baseline``).
"""

from . import checkers as _checkers  # noqa: F401  (registers the suite)
from .core import (  # noqa: F401
    CHECKERS,
    Checker,
    Finding,
    SourceFile,
    collect_files,
    compare_baseline,
    load_baseline,
    run_lint,
    save_baseline,
)

__all__ = [
    "CHECKERS", "Checker", "Finding", "SourceFile", "collect_files",
    "compare_baseline", "load_baseline", "run_lint", "save_baseline",
]
