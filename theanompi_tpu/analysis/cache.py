"""Result cache for the lint CLI (``.tpulint_cache/``).

Two granularities, both keyed so a stale hit is impossible:

* **tree entries** — the complete finding list of one invocation, keyed
  on sha256 over (analysis-source fingerprint, checker selection,
  explicit path arguments, every in-scope file's content hash).  An
  unchanged tree re-run is one hash pass + one JSON read: the tier-1
  gate drops from ~7 s to sub-second.
* **per-file entries** — the FILE-scoped checkers' findings for one
  file, keyed on (file content sha256, analysis fingerprint, the
  file-scoped checker selection).  On a tree miss (one file edited),
  unchanged files splice their cached findings in and skip
  ``check_file``; program/project checkers re-run live — they are
  whole-program by definition, so only their work is repeated.

The **analysis fingerprint** hashes every ``analysis/`` source file, so
editing any checker, the engine, or this module invalidates everything
automatically — there is no manually-bumped version to forget.  Writes
are atomic (tmp + rename) and every cache failure degrades to a normal
uncached run: the cache can slow a run down, never corrupt one.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding

CACHE_DIR_NAME = ".tpulint_cache"
SCHEMA = 1                 # bump when the entry layout itself changes
_TREE_KEEP = 64            # pruning caps (newest kept)
_FILE_KEEP = 4096


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def analysis_fingerprint() -> str:
    """sha256 over every ``analysis/`` source — the auto-invalidation
    key: any checker/engine/cache edit changes it."""
    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(here):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), here)
            h.update(rel.encode())
            with open(os.path.join(dirpath, fn), "rb") as f:
                h.update(_sha(f.read()).encode())
    return h.hexdigest()


def file_hashes(root: str, rels: Sequence[str]) -> List[Tuple[str, str]]:
    """(repo-relative path, content sha256) for every file; unreadable
    files hash to a unique marker so they can never produce a hit."""
    out = []
    for rel in rels:
        try:
            with open(os.path.join(root, rel), "rb") as f:
                out.append((rel.replace(os.sep, "/"), _sha(f.read())))
        except OSError:
            out.append((rel.replace(os.sep, "/"), f"unreadable:{rel}"))
    return out


def tree_key(analysis_fp: str, checker_names: Sequence[str],
             path_args: Sequence[str],
             hashes: Sequence[Tuple[str, str]]) -> str:
    payload = json.dumps({
        "schema": SCHEMA,
        "analysis": analysis_fp,
        "checkers": sorted(checker_names),
        "paths": list(path_args),
        "files": sorted(hashes),
    }, sort_keys=True)
    return _sha(payload.encode())


def file_key(analysis_fp: str, file_checkers: Sequence[str],
             content_sha: str) -> str:
    payload = json.dumps({
        "schema": SCHEMA,
        "analysis": analysis_fp,
        "checkers": sorted(file_checkers),
        "sha": content_sha,
    }, sort_keys=True)
    return _sha(payload.encode())


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------

class LintCache:
    """Filesystem store under ``<root>/.tpulint_cache`` (or an explicit
    ``cache_dir`` — the precommit hook roots the lint at a temp
    checkout of the index but keeps the repo's cache).  Every method is
    failure-tolerant: IO errors read as misses / silent no-ops."""

    def __init__(self, root: str, cache_dir: Optional[str] = None):
        self.dir = cache_dir or os.path.join(root, CACHE_DIR_NAME)

    def _path(self, kind: str, key: str) -> str:
        return os.path.join(self.dir, kind, key[:32] + ".json")

    def _load(self, kind: str, key: str) -> Optional[List[Finding]]:
        try:
            with open(self._path(kind, key), encoding="utf-8") as f:
                data = json.load(f)
            if data.get("schema") != SCHEMA:
                return None
            return [Finding(d["check"], d["path"], d["line"], d["col"],
                            d["message"]) for d in data["findings"]]
        except (OSError, KeyError, TypeError, ValueError):
            return None

    def _store(self, kind: str, key: str, findings: Sequence[Finding],
               keep: int) -> None:
        try:
            d = os.path.join(self.dir, kind)
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump({"schema": SCHEMA,
                           "findings": [x.to_dict() for x in findings]},
                          f)
            os.replace(tmp, self._path(kind, key))
            self._prune(d, keep)
        except OSError:
            pass

    @staticmethod
    def _prune(d: str, keep: int) -> None:
        try:
            entries = [(e.stat().st_mtime, e.path)
                       for e in os.scandir(d) if e.name.endswith(".json")]
            if len(entries) <= keep:
                return
            entries.sort()
            for _, path in entries[:len(entries) - keep]:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        except OSError:
            pass

    # -- tree level --------------------------------------------------------

    def load_tree(self, key: str) -> Optional[List[Finding]]:
        return self._load("tree", key)

    def store_tree(self, key: str, findings: Sequence[Finding]) -> None:
        self._store("tree", key, findings, _TREE_KEEP)

    # -- per-file level ----------------------------------------------------

    def load_file(self, key: str) -> Optional[List[Finding]]:
        return self._load("files", key)

    def store_file(self, key: str, findings: Sequence[Finding]) -> None:
        self._store("files", key, findings, _FILE_KEEP)
