"""rng-discipline: a ``jax.random`` key must not feed two draws.

The invariant (docs/design.md §12): consuming one key twice makes the
two draws bit-identical — the exact bug class the fused GoSGD
``fold_in(count)`` stream exists to prevent (docs/design.md §8: every
gossip draw derives from ``fold_in(key, count)`` so the in-scan cadence
draws like k standalone calls).  ``fold_in`` is therefore NOT counted
as consumption — deriving several independent streams from one key with
distinct fold data is the sanctioned pattern; ``split`` and every
sampler are.

Analysis is per-function and per-block: statements scan linearly; a
name passed as the key argument to a sampler (or ``split``) is marked
consumed, a store to the name clears it, and a second consumption
without an interleaving rebinding is a finding.  Branch bodies analyze
against a COPY of the state (an if/else where each arm draws once is
fine), which trades a little recall for zero false positives on
exclusive paths.  A loop body that consumes a key defined outside the
loop without ever rebinding it is flagged too — the classic
``for i: x = normal(key)`` freeze.

Interprocedural (the whole-program engine): a call to a function whose
summary says it CONSUMES one of its parameters as a key — directly, or
by passing it on to a consuming callee, fixpointed across the repo-wide
call graph — consumes the name passed at that position, exactly like a
direct sampler call.  ``draw(key); draw(key)`` through a helper one
module away is now the same finding as ``normal(key); normal(key)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..core import Checker, Finding, SourceFile, register
from ..engine import ProgramIndex, consumed_key_name

_BLOCK_FIELDS = ("body", "orelse", "finalbody")


@register
class RngDisciplineChecker(Checker):
    name = "rng-discipline"
    description = ("a jax.random key consumed by two draws (direct or "
                   "through key-consuming callees) with no interleaving "
                   "split/fold_in")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        out: List[Finding] = []
        for sf in index.files:
            out.extend(self._check_file(index, sf))
        return out

    def _check_file(self, index: ProgramIndex, sf: SourceFile):
        self._index = index
        self._sf = sf
        self._fidx = index.file_index[sf.path]
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_block(sf, self._stmts(node), {}, findings)
            elif isinstance(node, ast.Lambda):
                self._scan_exprs(sf, node.body, {}, findings)
        # one diagnostic per call site (the loop walk and the linear walk
        # can both describe the same reuse)
        seen, out = set(), []
        for f in findings:
            if (f.line, f.col) not in seen:
                seen.add((f.line, f.col))
                out.append(f)
        return out

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _stmts(fn):
        body = getattr(fn, "body", None)
        return body if isinstance(body, list) else []

    def _key_names(self, sf: SourceFile, call: ast.Call) -> List[str]:
        """Names consumed as keys by this call: the key argument of a
        direct ``jax.random.<sampler>``, plus every Name passed at a
        position the (engine-resolved) callee's summary consumes."""
        resolved = sf.resolver.resolve(call.func)
        if resolved and resolved.startswith("jax.random."):
            direct = consumed_key_name(call, sf.resolver)
            return [direct] if direct is not None else []
        out: List[str] = []
        enclosing = self._fidx.enclosing.get(id(call.func))
        for tgt in self._index.resolve_call(sf, call.func, enclosing):
            kp = self._index.key_params(tgt)
            if not kp:
                continue
            tparams = tgt.params()
            for i in kp:
                arg = call.args[i] if i < len(call.args) else None
                for kw in call.keywords:
                    if i < len(tparams) and kw.arg == tparams[i]:
                        arg = kw.value
                if isinstance(arg, ast.Name) and arg.id not in out:
                    out.append(arg.id)
        return out

    def _calls_in_order(self, node):
        """Calls in (approximate) evaluation order within one statement,
        not descending into lambdas or nested function defs (their
        bodies run later, in their own scope with their own fresh
        parameters — analyzed separately)."""
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.Lambda, ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                continue
            if isinstance(sub, ast.Call):
                yield sub
            stack.extend(ast.iter_child_nodes(sub))

    @staticmethod
    def _stores(node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                        (ast.Store,
                                                         ast.Del)):
                yield sub.id

    def _scan_block(self, sf: SourceFile, stmts, consumed: Dict[str, int],
                    findings: List[Finding]) -> None:
        """Linear scan; ``consumed`` maps key name → line it was spent."""
        for st in stmts:
            # nested function definitions analyze independently (their
            # bodies run later, against their own keys)
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                self._scan_loop(sf, st, dict(consumed), findings)
                # conservatively clear names the loop stores
                for n in self._stores(st):
                    consumed.pop(n, None)
                continue
            if isinstance(st, (ast.If, ast.Try, ast.With, ast.AsyncWith)):
                header = getattr(st, "test", None) or \
                    getattr(st, "items", None)
                if header is not None:
                    items = header if isinstance(header, list) else [header]
                    for h in items:
                        h_node = getattr(h, "context_expr", h)
                        self._scan_exprs(sf, h_node, consumed, findings)
                for fieldname in _BLOCK_FIELDS:
                    sub = getattr(st, fieldname, None)
                    if sub:
                        self._scan_block(sf, sub, dict(consumed), findings)
                for h in getattr(st, "handlers", []):
                    self._scan_block(sf, h.body, dict(consumed), findings)
                # conservative: anything stored in any arm is fresh after
                for n in self._stores(st):
                    consumed.pop(n, None)
                continue
            # plain statement: consume keys in expression order, then
            # apply stores (``key, sub = split(key)`` consumes THEN
            # rebinds — correct and no finding)
            self._scan_exprs(sf, st, consumed, findings)
            for n in self._stores(st):
                consumed.pop(n, None)

    def _scan_exprs(self, sf, node, consumed, findings,
                    soft=frozenset()) -> None:
        """Expression scan, exclusive-path aware: the arms of an
        ``a if c else b`` (and the short-circuited tail of and/or
        chains) consume against a state COPY — only one arm runs, so a
        draw in each is NOT reuse.  ``soft`` holds names whose prior
        consumption happened OUTSIDE the current conditional position:
        a first in-arm use of such a name is "maybe reuse" (the arm may
        never run) and is not reported, but it re-arms the name so a
        SECOND in-arm use still is.  Consumption in BOTH arms of an
        IfExp merges back as definite.  Lambdas are their own scope."""
        if node is None or isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.IfExp):
            self._scan_exprs(sf, node.test, consumed, findings, soft)
            arms = []
            for arm in (node.body, node.orelse):
                state = dict(consumed)
                self._scan_exprs(sf, arm, state, findings,
                                 soft=set(consumed))
                arms.append(state)
            # consumed in BOTH arms = definitely consumed (one arm runs)
            for name in set(arms[0]) & set(arms[1]):
                consumed.setdefault(name, min(arms[0][name],
                                              arms[1][name]))
            return
        if isinstance(node, ast.BoolOp):
            self._scan_exprs(sf, node.values[0], consumed, findings, soft)
            for v in node.values[1:]:     # may be short-circuited away
                self._scan_exprs(sf, v, dict(consumed), findings,
                                 soft=set(consumed))
            return
        if isinstance(node, ast.Call):
            # args evaluate before the outer call consumes its key; the
            # soft set is SHARED down the whole arm (created mutable at
            # branch entry) so a first soft use re-arms for siblings too
            for sub in list(node.args) + [kw.value for kw in node.keywords]:
                self._scan_exprs(sf, sub, consumed, findings, soft)
            self._scan_exprs(sf, node.func, consumed, findings, soft)
            for name in self._key_names(sf, node):
                if name in consumed and name in soft:
                    soft.discard(name)    # re-armed: next in-arm use reports
                    consumed[name] = node.lineno
                elif name in consumed:
                    findings.append(Finding(
                        self.name, sf.path, node.lineno, node.col_offset,
                        f"key `{name}` consumed again (first spent on "
                        f"line {consumed[name]}) with no interleaving "
                        "split/fold_in — both draws are bit-identical"))
                else:
                    consumed[name] = node.lineno
            return
        for child in ast.iter_child_nodes(node):
            self._scan_exprs(sf, child, consumed, findings, soft)

    def _scan_loop(self, sf, loop, consumed, findings) -> None:
        """Flag keys consumed inside a loop body that the body never
        rebinds — every iteration replays the same draw."""
        body_stores = set()
        for st in loop.body + getattr(loop, "orelse", []):
            body_stores.update(self._stores(st))
        if isinstance(loop, (ast.For, ast.AsyncFor)):
            body_stores.update(self._stores(loop.target))

        for st in loop.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for call in self._calls_in_order(st):
                for name in self._key_names(sf, call):
                    if name not in body_stores:
                        findings.append(Finding(
                            self.name, sf.path, call.lineno,
                            call.col_offset,
                            f"key `{name}` consumed inside a loop "
                            "without re-split/fold_in — every iteration "
                            "draws the same bits"))
        # and the body itself scans linearly for straight-line reuse
        self._scan_block(sf, loop.body, dict(consumed), findings)
