"""sharding-schema: PartitionSpec literals must fit the mesh and the
function they annotate.

Two invariants (docs/design.md §12), guarding the ROADMAP-item-5
universal sharded-update wrapper before it exists:

1. **Spec axis names are real.**  Every string entry of a
   ``PartitionSpec`` literal (``P('workers', None)``, tuple entries
   ``P(('workers', 'model'))`` included) must name a declared mesh axis
   — the ``parallel/mesh.py`` ``*_AXIS`` constants plus axes literally
   declared in the same file, exactly the vocabulary
   collective-discipline validates collectives against.  A typo'd axis
   in a spec places every leaf REPLICATED (jax treats an unknown name
   as an error only at mesh-bind time, often far from the literal).
   ``P(None, *base)``-style star constructions (``steps.stage_window``)
   are recognized: literal entries are checked, the starred tail is
   skipped, never guessed.

2. **shard_map specs match the callee.**  For ``shard_map(f, mesh=...,
   in_specs=(...), out_specs=...)`` where ``f`` resolves to a visible
   def/lambda: a literal ``in_specs`` tuple must have exactly one entry
   per positional parameter of ``f``, and a literal ``out_specs`` tuple
   must match the arity of ``f``'s literal ``return`` tuples.  A
   wrong-length spec tuple compiles into the WRONG argument→sharding
   pairing (or a trace error three layers away from the edit).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Checker, Finding, SourceFile, register
from ..engine import ProgramIndex
from .collective_discipline import CollectiveDisciplineChecker

PSPEC_NAMES = {"jax.sharding.PartitionSpec",
               "jax.interpreters.pxla.PartitionSpec"}

SHARD_MAP_NAMES = {
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "theanompi_tpu.jax_compat.shard_map",
}


def _is_pspec_call(sf: SourceFile, node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and \
        sf.resolver.resolve(node.func) in PSPEC_NAMES


@register
class ShardingSchemaChecker(Checker):
    name = "sharding-schema"
    description = ("PartitionSpec literals checked against mesh axis "
                   "names; shard_map in_specs/out_specs arity checked "
                   "against the callee signature")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        # reuse collective-discipline's axis vocabulary (one source of
        # truth for what counts as a declared axis)
        cd = CollectiveDisciplineChecker()
        declared = cd._declared_axes(index)
        findings: List[Finding] = []
        for sf in index.files:
            valid = declared | cd._file_axes(sf)
            for node in ast.walk(sf.tree):
                if _is_pspec_call(sf, node):
                    self._check_spec_literal(sf, node, valid, findings)
                elif isinstance(node, ast.Call) and \
                        sf.resolver.resolve(node.func) in SHARD_MAP_NAMES:
                    self._check_shard_map(index, sf, node, findings)
        return findings

    # -- 1: axis names inside P literals -----------------------------------

    def _check_spec_literal(self, sf: SourceFile, call: ast.Call,
                            valid: Set[str],
                            findings: List[Finding]) -> None:
        def check_entry(e: ast.AST) -> None:
            if isinstance(e, ast.Starred):
                return                      # P(None, *base): tail unknown
            if isinstance(e, ast.Constant):
                if isinstance(e.value, str) and e.value not in valid:
                    findings.append(Finding(
                        self.name, sf.path, e.lineno, e.col_offset,
                        f"PartitionSpec names undeclared mesh axis "
                        f"'{e.value}' (declared: "
                        f"{', '.join(sorted(valid))})"))
                return
            if isinstance(e, (ast.Tuple, ast.List)):
                for sub in e.elts:
                    check_entry(sub)

        for e in call.args:
            check_entry(e)

    # -- 2: shard_map in_specs/out_specs arity -----------------------------

    def _check_shard_map(self, index: ProgramIndex, sf: SourceFile,
                         call: ast.Call,
                         findings: List[Finding]) -> None:
        fn_arg = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg == "f":
                fn_arg = kw.value
        if fn_arg is None:
            return
        n_params, has_vararg, returns = self._callee_shape(index, sf,
                                                           call, fn_arg)
        if n_params is None:
            return
        in_specs = out_specs = None
        for kw in call.keywords:
            if kw.arg == "in_specs":
                in_specs = kw.value
            elif kw.arg == "out_specs":
                out_specs = kw.value
        fname = getattr(fn_arg, "attr", None) or \
            getattr(fn_arg, "id", "<lambda>")
        if isinstance(in_specs, (ast.Tuple, ast.List)) and \
                not any(isinstance(e, ast.Starred) for e in in_specs.elts):
            n_specs = len(in_specs.elts)
            ok = n_specs == n_params or (has_vararg and
                                         n_specs >= n_params)
            if not ok:
                findings.append(Finding(
                    self.name, sf.path, in_specs.lineno,
                    in_specs.col_offset,
                    f"shard_map in_specs has {n_specs} spec(s) but "
                    f"`{fname}` takes {n_params} positional "
                    "parameter(s) — every argument needs exactly one "
                    "spec"))
        if isinstance(out_specs, (ast.Tuple, ast.List)) and \
                not any(isinstance(e, ast.Starred)
                        for e in out_specs.elts) and returns:
            n_specs = len(out_specs.elts)
            bad = [r for r in returns if r != n_specs]
            if bad and all(r != n_specs for r in returns):
                findings.append(Finding(
                    self.name, sf.path, out_specs.lineno,
                    out_specs.col_offset,
                    f"shard_map out_specs has {n_specs} spec(s) but "
                    f"`{fname}` returns {bad[0]} value(s)"))

    def _callee_shape(self, index: ProgramIndex, sf: SourceFile,
                      call: ast.Call, fn_arg: ast.AST):
        """(positional param count, has_vararg, literal return-tuple
        arities) of the shard_map'd callable, or (None, ..) when it is
        not statically visible."""
        node = None
        if isinstance(fn_arg, ast.Lambda):
            node = fn_arg
        elif isinstance(fn_arg, (ast.Name, ast.Attribute)):
            fidx = index.file_index[sf.path]
            enc = fidx.enclosing.get(id(fn_arg))
            targets = index.resolve_call(sf, fn_arg, enc)
            if len(targets) == 1:
                node = targets[0].node
            elif targets:
                # several overrides: check only when they agree on arity
                counts = {self._param_count(t.node)[0] for t in targets}
                if len(counts) == 1:
                    node = targets[0].node
        if node is None:
            return None, False, []
        n, vararg = self._param_count(node)
        returns: List[int] = []
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            from ..engine import body_walk
            for sub in body_walk(node):
                if isinstance(sub, ast.Return) and \
                        isinstance(sub.value, ast.Tuple):
                    returns.append(len(sub.value.elts))
        return n, vararg, returns

    @staticmethod
    def _param_count(node: ast.AST):
        a = node.args
        params = [p.arg for p in list(a.posonlyargs) + list(a.args)
                  if p.arg not in ("self", "cls")]
        return len(params), a.vararg is not None
