"""telemetry-hot-path: recording calls on hot paths guard on ``enabled``.

The invariant (docs/design.md §11 cost contract, machine-checked per
§12): disabled telemetry must cost ONE attribute check per hot-path
site.  ``telemetry.active()`` returns the inert ``DISABLED`` singleton,
and every recording call (``counter``/``gauge``/``observe``/``phase``/
``event``/...) in the four hot-path files — ``parallel/steps.py``,
``models/data/prefetch.py``, ``parallel/exchanger.py``, ``worker.py``
— must sit under an ``if <handle>.enabled:`` (or an ``... if
x.enabled else ...`` expression).  An unguarded call still "works"
(the DISABLED methods are no-ops) which is exactly why review misses
it: the cost is a per-iteration method dispatch + argument
construction that only shows up as throughput noise at pod scale.

Handles are found by dataflow: names assigned from
``telemetry.active()`` / ``telemetry.init(...)`` / ``self.telemetry``,
the dotted ``self.telemetry`` itself, and direct module-level
``telemetry.<record>()`` calls.  The guard test must mention
``.enabled`` (``if tm.enabled``, ``if rec and telem.enabled``); the
accessors (``active``/``init``/``install_signal_hooks``) and plain
``.enabled`` reads are free.

Round 16 extends the pass to the span-emission API
(``utils/tracing.py``, docs/design.md §17): tracer handles come from
``tracing.active()``/``tracing.init(...)``, ``Tracer.begin`` is the
recording gate (a Span minted under a guard only exists on the enabled
path, so ``Span.end``/``note`` need no separate check), and the
module-level ``tracing.emit_wire_span``/``emit_server_span`` one-shot
emitters are recording calls — an unguarded hot-path span is a lint
finding.  The hot set grows the wire/center/island files the span API
rides through.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..core import Checker, Finding, ImportResolver, SourceFile, register

HOT_BASENAMES = {"steps.py", "prefetch.py", "exchanger.py", "worker.py",
                 "async_easgd.py", "wire.py", "center_server.py",
                 "fleetmon.py", "numerics.py"}

TELEMETRY_MODULE = "theanompi_tpu.utils.telemetry"
TRACING_MODULE = "theanompi_tpu.utils.tracing"
FLEETMON_MODULE = "theanompi_tpu.utils.fleetmon"
NUMERICS_MODULE = "theanompi_tpu.utils.numerics"

# methods that record (cost when disabled = wasted work); the accessors
# and `.enabled` reads are the sanctioned unguarded surface.  `begin`
# (Tracer) and the emit_* one-shot helpers are the §17 span API;
# `emit_alert` is the §20 fleet-health alert emitter (fleetmon.py joins
# the hot set — its streamer/collector record into the same registry);
# `record` is the §25 numerics report emitter (numerics.py joins too).
RECORDING = {"counter", "gauge", "observe", "phase", "event",
             "system_snapshot", "dump_flight", "tail", "summary", "close",
             "begin", "emit_wire_span", "emit_server_span", "emit_alert",
             "record"}

HANDLE_SOURCES = {TELEMETRY_MODULE + ".active", TELEMETRY_MODULE + ".init",
                  TRACING_MODULE + ".active", TRACING_MODULE + ".init"}


def _test_mentions_enabled(test: ast.AST) -> bool:
    """True when the test DOMINATES on ``enabled``: the body is only
    reachable with the check true.  That's the bare read, an ``and``
    chain with an enabled conjunct, or an ``or`` whose EVERY alternative
    guards — `other() or tm.enabled` does NOT guard (the body runs with
    telemetry off through the left arm)."""
    if isinstance(test, ast.Attribute) and test.attr == "enabled":
        return True
    if isinstance(test, ast.Name) and test.id == "enabled":
        return True
    if isinstance(test, ast.BoolOp):
        if isinstance(test.op, ast.And):
            return any(_test_mentions_enabled(v) for v in test.values)
        return all(_test_mentions_enabled(v) for v in test.values)
    return False


def _test_negates_enabled(test: ast.AST) -> bool:
    """``not tm.enabled`` (the early-exit guard idiom)."""
    return isinstance(test, ast.UnaryOp) and \
        isinstance(test.op, ast.Not) and \
        _test_mentions_enabled(test.operand)


def _ends_control_flow(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Continue, ast.Break, ast.Raise))


@register
class TelemetryHotPathChecker(Checker):
    name = "telemetry-hot-path"
    description = ("telemetry/span-emission calls in steps/prefetch/"
                   "exchanger/worker/async_easgd/wire/center_server not "
                   "dominated by an `enabled` check")

    def applies_to(self, path: str) -> bool:
        return path.rsplit("/", 1)[-1] in HOT_BASENAMES

    def check_file(self, sf: SourceFile):
        handles = self._collect_handles(sf)
        findings: List[Finding] = []
        self._scan_block(sf, sf.tree.body, handles, False, findings)
        return findings

    # -- handle discovery --------------------------------------------------

    def _collect_handles(self, sf: SourceFile) -> Set[str]:
        handles: Set[str] = {"self.telemetry"}
        changed = True
        while changed:
            changed = False
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Assign):
                    continue
                src = None
                v = node.value
                if isinstance(v, ast.Call):
                    resolved = sf.resolver.resolve(v.func)
                    if resolved in HANDLE_SOURCES:
                        src = True
                elif isinstance(v, (ast.Name, ast.Attribute)):
                    if ImportResolver.dotted(v) in handles:
                        src = True
                if not src:
                    continue
                for t in node.targets:
                    name = ImportResolver.dotted(t)
                    if name and name not in handles:
                        handles.add(name)
                        changed = True
        return handles

    # -- guarded walk ------------------------------------------------------
    # Block-based so DOMINANCE is modeled, not just lexical nesting:
    # `if tm.enabled:` guards its body, `if not tm.enabled: return`
    # guards the REST of the enclosing block (the early-exit idiom), an
    # `elif tm.enabled:` arm guards its own body (If nodes in orelse
    # lists get the same treatment as top-level ones), and
    # `x if tm.enabled else y` guards its true arm.

    def _scan_block(self, sf, stmts, handles: Set[str], guarded: bool,
                    findings: List[Finding]) -> None:
        for st in stmts:
            if isinstance(st, ast.If):
                self._scan_expr(sf, st.test, handles, guarded, findings)
                body_guarded = guarded or _test_mentions_enabled(st.test)
                neg = _test_negates_enabled(st.test)
                self._scan_block(sf, st.body, handles, body_guarded,
                                 findings)
                self._scan_block(sf, st.orelse, handles, guarded or neg,
                                 findings)
                if neg and _ends_control_flow(st.body):
                    # `if not tm.enabled: return` — everything after is
                    # only reachable with telemetry on
                    guarded = True
                continue
            # other statements: scan expressions, recurse into any
            # nested blocks (loops, with, try, function/class bodies —
            # a def under a guard inherits it: the closure is only
            # created on the enabled path)
            for fieldname, value in ast.iter_fields(st):
                if isinstance(value, list) and value and \
                        isinstance(value[0], ast.stmt):
                    self._scan_block(sf, value, handles, guarded, findings)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.excepthandler):
                            self._scan_block(sf, v.body, handles, guarded,
                                             findings)
                        elif isinstance(v, ast.AST):
                            self._scan_expr(sf, v, handles, guarded,
                                            findings)
                elif isinstance(value, ast.AST):
                    self._scan_expr(sf, value, handles, guarded, findings)

    def _scan_expr(self, sf, node, handles, guarded, findings) -> None:
        if node is None:
            return
        if isinstance(node, ast.IfExp):
            self._scan_expr(sf, node.test, handles, guarded, findings)
            body_guarded = guarded or _test_mentions_enabled(node.test)
            self._scan_expr(sf, node.body, handles, body_guarded, findings)
            self._scan_expr(sf, node.orelse, handles,
                            guarded or _test_negates_enabled(node.test),
                            findings)
            return
        if isinstance(node, ast.Call):
            self._check_call(sf, node, handles, guarded, findings)
        for child in ast.iter_child_nodes(node):
            self._scan_expr(sf, child, handles, guarded, findings)

    def _check_call(self, sf, node, handles, guarded, findings) -> None:
        if guarded:
            return
        func = node.func
        if not isinstance(func, ast.Attribute) or \
                func.attr not in RECORDING:
            return
        base = ImportResolver.dotted(func.value)
        resolved_base = sf.resolver.resolve(func.value)
        is_handle = (base in handles) or \
            (resolved_base in (TELEMETRY_MODULE, TRACING_MODULE,
                               FLEETMON_MODULE, NUMERICS_MODULE))
        if is_handle:
            findings.append(Finding(
                self.name, sf.path, node.lineno, node.col_offset,
                f"unguarded telemetry call `{base}.{func.attr}(...)` on a "
                "hot path — wrap in `if <handle>.enabled:` (one attribute "
                "check when disabled, docs/design.md §11)"))
