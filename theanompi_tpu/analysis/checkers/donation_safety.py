"""donation-safety: a donated buffer must not be read after the call.

The invariant (docs/design.md §12, guarding the PR-3 AOT-cache rules):
``jax.jit(..., donate_argnums=...)`` hands the argument's HBM to the
callee — after the call the old array is invalid, and reading it is
use-after-free that jax only sometimes catches (and a deserialized AOT
executable on this container's CPU backend turns into heap corruption,
which is why ``compile_cache.donated_load_safe`` exists at all).

Per-scope analysis: the checker records names bound to
``jax.jit(..., donate_argnums=...)`` with their donated positional
indices (literal argnums, or argnames mapped through an inline
lambda's signature; an unresolvable spec is skipped rather than
guessed — a wrong guess would flag the wrong argument), then scans the
scope linearly —
a call through such a name marks the argument names/dotted paths at the
donated positions as dead, a store revives them, and any later read is
a finding.  The ``state = train_fn(state, ...)`` rebind idiom is
recognized: consuming and rebinding in one statement is the sanctioned
in-place-update shape.  Branch bodies scan against a state copy, so
exclusive arms cannot poison each other.

Interprocedural (the whole-program engine): module-level donating
callables are collected REPO-WIDE and resolved through each file's
import table, so ``from train import step_fn`` — where ``train.py``
holds ``step_fn = jax.jit(g, donate_argnums=0)`` — flags a
read-after-donate at the importing call site too.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Checker, Finding, ImportResolver, SourceFile, register
from ..engine import ProgramIndex

_JIT_NAMES = {"jax.jit"}


def _donated_indices(call: ast.Call) -> Optional[Set[int]]:
    """Donated positional indices of a jax.jit call, or None when the
    call donates nothing — or when the spec cannot be resolved
    STATICALLY (non-literal argnums, argnames against an opaque
    callee): guessing an index would flag the wrong argument while
    waving the donated one through, so unresolvable specs are skipped.
    ``donate_argnames`` resolves when the jitted callee is an inline
    lambda/visible signature (names map to positional slots)."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                idx = {e.value for e in v.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, int)}
                if idx:
                    return idx
            return None
        if kw.arg == "donate_argnames":
            names = _literal_names(kw.value)
            params = _callee_params(call)
            if names and params:
                idx = {params.index(n) for n in names if n in params}
                if idx:
                    return idx
            return None
    return None


def _literal_names(v: ast.AST) -> Set[str]:
    if isinstance(v, ast.Constant) and isinstance(v.value, str):
        return {v.value}
    if isinstance(v, (ast.Tuple, ast.List)):
        return {e.value for e in v.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)}
    return set()


def _callee_params(call: ast.Call) -> Optional[list]:
    """Positional parameter names of the jitted callee, when visible
    (an inline lambda)."""
    if call.args and isinstance(call.args[0], ast.Lambda):
        a = call.args[0].args
        return [p.arg for p in list(a.posonlyargs) + list(a.args)]
    return None


@register
class DonationSafetyChecker(Checker):
    name = "donation-safety"
    description = ("a name passed through a donate_argnums call site and "
                   "read afterwards in the same scope (donating callables "
                   "resolved repo-wide)")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        # module-level donating callables, repo-wide, by absolute dotted
        # name — visible through any file's import table
        self._global_fns: Dict[str, Set[int]] = {}
        for sf in index.files:
            module = sf.resolver.module
            for name, idx in self._collect_donating_fns(sf,
                                                        sf.tree).items():
                if "." not in name:    # dotted targets stay file-local
                    self._global_fns[f"{module}.{name}"] = idx
        findings: List[Finding] = []
        for sf in index.files:
            findings.extend(self._check_one(sf))
        return findings

    def _check_one(self, sf: SourceFile):
        findings: List[Finding] = []
        # module-level donating names (`f = jax.jit(g, donate_argnums=0)`
        # at top level) are visible from every function scope — merge
        # them under each scope's own collection
        module_fns = self._collect_donating_fns(sf, sf.tree)
        scopes = [sf.tree] + [n for n in ast.walk(sf.tree)
                              if isinstance(n, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))]
        for scope in scopes:
            donated_fns = dict(module_fns)
            if scope is not sf.tree:
                donated_fns.update(self._collect_donating_fns(sf, scope))
            body = scope.body if isinstance(scope.body, list) else []
            self._scan_block(sf, body, donated_fns, {}, findings)
        return findings

    # -- pass 1: which names are donating jitted callables -----------------

    def _collect_donating_fns(self, sf: SourceFile, scope
                              ) -> Dict[str, Set[int]]:
        out: Dict[str, Set[int]] = {}
        for st in self._shallow_stmts(scope):
            if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
                resolved = sf.resolver.resolve(st.value.func)
                if resolved in _JIT_NAMES:
                    idx = _donated_indices(st.value)
                    if idx:
                        for t in st.targets:
                            name = ImportResolver.dotted(t)
                            if name:
                                out[name] = idx
        return out

    @staticmethod
    def _shallow_stmts(scope):
        """Statements of this scope, not descending into nested defs."""
        stack = list(scope.body) if isinstance(scope.body, list) else []
        while stack:
            st = stack.pop()
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            yield st
            for fieldname in ("body", "orelse", "finalbody"):
                stack.extend(getattr(st, fieldname, []) or [])
            for h in getattr(st, "handlers", []):
                stack.extend(h.body)

    # -- pass 2: linear scan for read-after-donate -------------------------

    def _scan_block(self, sf, stmts, donated_fns: Dict[str, Set[int]],
                    dead: Dict[str, int], findings: List[Finding]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.If, ast.For, ast.AsyncFor, ast.While,
                               ast.Try, ast.With, ast.AsyncWith)):
                header = getattr(st, "test", None) or getattr(st, "iter",
                                                              None)
                if header is not None:
                    self._scan_stmt(sf, header, donated_fns, dead, findings,
                                    stores=())
                for fieldname in ("body", "orelse", "finalbody"):
                    sub = getattr(st, fieldname, None)
                    if sub:
                        self._scan_block(sf, sub, donated_fns, dict(dead),
                                         findings)
                for h in getattr(st, "handlers", []):
                    self._scan_block(sf, h.body, donated_fns, dict(dead),
                                     findings)
                for n in self._stored_names(st):
                    dead.pop(n, None)
                continue
            stores = tuple(self._stored_names(st))
            self._scan_stmt(sf, st, donated_fns, dead, findings, stores)
            for n in stores:
                dead.pop(n, None)

    def _scan_stmt(self, sf, node, donated_fns, dead, findings,
                   stores) -> None:
        """Reads first (a read of a dead name fires even when the same
        statement rebinds it later — ``y = x + f(x_dead)``), then the
        donations this statement performs."""
        # 1. reads of dead names (a dead name in callee position is fine
        #    — only a donated fn's DATA args die, not the callable)
        call_funcs = {id(sub.func) for sub in ast.walk(node)
                      if isinstance(sub, ast.Call)}
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(sub, "ctx", None), ast.Load):
                name = ImportResolver.dotted(sub)
                if name in dead and id(sub) not in call_funcs:
                    findings.append(Finding(
                        self.name, sf.path, sub.lineno, sub.col_offset,
                        f"`{name}` read after being donated on line "
                        f"{dead[name]} (donate_argnums hands its buffer "
                        "to the callee; rebind the result instead)"))
                    dead.pop(name)      # report once per donation
        # 2. donations performed by calls in this statement
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            idx: Optional[Set[int]] = None
            fname = ImportResolver.dotted(sub.func)
            if fname and fname in donated_fns:
                idx = donated_fns[fname]
            elif isinstance(sub.func, ast.Call):
                resolved = sf.resolver.resolve(sub.func.func)
                if resolved in _JIT_NAMES:
                    idx = _donated_indices(sub.func)
            else:
                # a donating callable imported from another module
                resolved = sf.resolver.resolve(sub.func)
                if resolved is not None:
                    idx = getattr(self, "_global_fns", {}).get(resolved)
            if not idx:
                continue
            for i in idx:
                if i < len(sub.args):
                    name = ImportResolver.dotted(sub.args[i])
                    # rebind-in-place (`state = fn(state)`) is the
                    # sanctioned donation shape — not dead afterwards
                    if name and name not in stores:
                        dead[name] = sub.lineno

    @staticmethod
    def _stored_names(node):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(sub, "ctx", None),
                               (ast.Store, ast.Del)):
                name = ImportResolver.dotted(sub)
                if name:
                    yield name

