"""donation-safety: a donated buffer must not be read after the call —
plus shard-rebuild-dominance, the update-sharding escape gate.

The invariant (docs/design.md §12, guarding the PR-3 AOT-cache rules):
``jax.jit(..., donate_argnums=...)`` hands the argument's HBM to the
callee — after the call the old array is invalid, and reading it is
use-after-free that jax only sometimes catches (and a deserialized AOT
executable on this container's CPU backend turns into heap corruption,
which is why ``compile_cache.donated_load_safe`` exists at all).

Per-scope analysis: the checker records names bound to
``jax.jit(..., donate_argnums=...)`` with their donated positional
indices (literal argnums, or argnames mapped through an inline
lambda's signature; an unresolvable spec is skipped rather than
guessed — a wrong guess would flag the wrong argument), then scans the
scope linearly —
a call through such a name marks the argument names/dotted paths at the
donated positions as dead, a store revives them, and any later read is
a finding.  The ``state = train_fn(state, ...)`` rebind idiom is
recognized: consuming and rebinding in one statement is the sanctioned
in-place-update shape.  Branch bodies scan against a state copy, so
exclusive arms cannot poison each other.

Interprocedural (the whole-program engine): module-level donating
callables are collected REPO-WIDE and resolved through each file's
import table, so ``from train import step_fn`` — where ``train.py``
holds ``step_fn = jax.jit(g, donate_argnums=0)`` — flags a
read-after-donate at the importing call site too.

shard-rebuild-dominance (docs/design.md §23): the update-sharding
wrapper (``parallel/update_sharding.py``) cuts worker-local chunks out
of full buffers (``slice_chunk``/``shard_tree``) that are only valid
shard-wide — under the ``_build_exchange_fn`` ``donate_argnums=(0,)``
contract, a function that lets such a chunk ESCAPE (return it) without
its allgather rebuild silently replaces a donated full buffer with a
1/N-sized local shard.  The checker taints names bound from the named
producers, propagates through arithmetic/containers (never through
arbitrary calls — an optimizer update of a chunk is a new value the
schema owns), clears taint only when a rebuild
(``all_gather_chunks``/``unshard_tree``/``all_gather``) DOMINATES the
return — a rebind inside one branch of an ``if`` does not count — and
exempts the schema's own named producer helpers (``shard_*``,
``reshard_*``, ``slice_*``, ``chunk_*``), whose very job is returning
chunks.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, ImportResolver, SourceFile, register
from ..engine import ProgramIndex

_JIT_NAMES = {"jax.jit"}


def _donated_indices(call: ast.Call) -> Optional[Set[int]]:
    """Donated positional indices of a jax.jit call, or None when the
    call donates nothing — or when the spec cannot be resolved
    STATICALLY (non-literal argnums, argnames against an opaque
    callee): guessing an index would flag the wrong argument while
    waving the donated one through, so unresolvable specs are skipped.
    ``donate_argnames`` resolves when the jitted callee is an inline
    lambda/visible signature (names map to positional slots)."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                idx = {e.value for e in v.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, int)}
                if idx:
                    return idx
            return None
        if kw.arg == "donate_argnames":
            names = _literal_names(kw.value)
            params = _callee_params(call)
            if names and params:
                idx = {params.index(n) for n in names if n in params}
                if idx:
                    return idx
            return None
    return None


def _literal_names(v: ast.AST) -> Set[str]:
    if isinstance(v, ast.Constant) and isinstance(v.value, str):
        return {v.value}
    if isinstance(v, (ast.Tuple, ast.List)):
        return {e.value for e in v.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)}
    return set()


def _callee_params(call: ast.Call) -> Optional[list]:
    """Positional parameter names of the jitted callee, when visible
    (an inline lambda)."""
    if call.args and isinstance(call.args[0], ast.Lambda):
        a = call.args[0].args
        return [p.arg for p in list(a.posonlyargs) + list(a.args)]
    return None


@register
class DonationSafetyChecker(Checker):
    name = "donation-safety"
    description = ("a name passed through a donate_argnums call site and "
                   "read afterwards in the same scope (donating callables "
                   "resolved repo-wide)")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        # module-level donating callables, repo-wide, by absolute dotted
        # name — visible through any file's import table
        self._global_fns: Dict[str, Set[int]] = {}
        for sf in index.files:
            module = sf.resolver.module
            for name, idx in self._collect_donating_fns(sf,
                                                        sf.tree).items():
                if "." not in name:    # dotted targets stay file-local
                    self._global_fns[f"{module}.{name}"] = idx
        findings: List[Finding] = []
        for sf in index.files:
            findings.extend(self._check_one(sf))
        return findings

    def _check_one(self, sf: SourceFile):
        findings: List[Finding] = []
        # module-level donating names (`f = jax.jit(g, donate_argnums=0)`
        # at top level) are visible from every function scope — merge
        # them under each scope's own collection
        module_fns = self._collect_donating_fns(sf, sf.tree)
        scopes = [sf.tree] + [n for n in ast.walk(sf.tree)
                              if isinstance(n, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))]
        for scope in scopes:
            donated_fns = dict(module_fns)
            if scope is not sf.tree:
                donated_fns.update(self._collect_donating_fns(sf, scope))
            body = scope.body if isinstance(scope.body, list) else []
            self._scan_block(sf, body, donated_fns, {}, findings)
        return findings

    # -- pass 1: which names are donating jitted callables -----------------

    def _collect_donating_fns(self, sf: SourceFile, scope
                              ) -> Dict[str, Set[int]]:
        out: Dict[str, Set[int]] = {}
        for st in self._shallow_stmts(scope):
            if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
                resolved = sf.resolver.resolve(st.value.func)
                if resolved in _JIT_NAMES:
                    idx = _donated_indices(st.value)
                    if idx:
                        for t in st.targets:
                            name = ImportResolver.dotted(t)
                            if name:
                                out[name] = idx
        return out

    @staticmethod
    def _shallow_stmts(scope):
        """Statements of this scope, not descending into nested defs."""
        stack = list(scope.body) if isinstance(scope.body, list) else []
        while stack:
            st = stack.pop()
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            yield st
            for fieldname in ("body", "orelse", "finalbody"):
                stack.extend(getattr(st, fieldname, []) or [])
            for h in getattr(st, "handlers", []):
                stack.extend(h.body)

    # -- pass 2: linear scan for read-after-donate -------------------------

    def _scan_block(self, sf, stmts, donated_fns: Dict[str, Set[int]],
                    dead: Dict[str, int], findings: List[Finding]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.If, ast.For, ast.AsyncFor, ast.While,
                               ast.Try, ast.With, ast.AsyncWith)):
                header = getattr(st, "test", None) or getattr(st, "iter",
                                                              None)
                if header is not None:
                    self._scan_stmt(sf, header, donated_fns, dead, findings,
                                    stores=())
                for fieldname in ("body", "orelse", "finalbody"):
                    sub = getattr(st, fieldname, None)
                    if sub:
                        self._scan_block(sf, sub, donated_fns, dict(dead),
                                         findings)
                for h in getattr(st, "handlers", []):
                    self._scan_block(sf, h.body, donated_fns, dict(dead),
                                     findings)
                for n in self._stored_names(st):
                    dead.pop(n, None)
                continue
            stores = tuple(self._stored_names(st))
            self._scan_stmt(sf, st, donated_fns, dead, findings, stores)
            for n in stores:
                dead.pop(n, None)

    def _scan_stmt(self, sf, node, donated_fns, dead, findings,
                   stores) -> None:
        """Reads first (a read of a dead name fires even when the same
        statement rebinds it later — ``y = x + f(x_dead)``), then the
        donations this statement performs."""
        # 1. reads of dead names (a dead name in callee position is fine
        #    — only a donated fn's DATA args die, not the callable)
        call_funcs = {id(sub.func) for sub in ast.walk(node)
                      if isinstance(sub, ast.Call)}
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(sub, "ctx", None), ast.Load):
                name = ImportResolver.dotted(sub)
                if name in dead and id(sub) not in call_funcs:
                    findings.append(Finding(
                        self.name, sf.path, sub.lineno, sub.col_offset,
                        f"`{name}` read after being donated on line "
                        f"{dead[name]} (donate_argnums hands its buffer "
                        "to the callee; rebind the result instead)"))
                    dead.pop(name)      # report once per donation
        # 2. donations performed by calls in this statement
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            idx: Optional[Set[int]] = None
            fname = ImportResolver.dotted(sub.func)
            if fname and fname in donated_fns:
                idx = donated_fns[fname]
            elif isinstance(sub.func, ast.Call):
                resolved = sf.resolver.resolve(sub.func.func)
                if resolved in _JIT_NAMES:
                    idx = _donated_indices(sub.func)
            else:
                # a donating callable imported from another module
                resolved = sf.resolver.resolve(sub.func)
                if resolved is not None:
                    idx = getattr(self, "_global_fns", {}).get(resolved)
            if not idx:
                continue
            for i in idx:
                if i < len(sub.args):
                    name = ImportResolver.dotted(sub.args[i])
                    # rebind-in-place (`state = fn(state)`) is the
                    # sanctioned donation shape — not dead afterwards
                    if name and name not in stores:
                        dead[name] = sub.lineno

    @staticmethod
    def _stored_names(node):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(sub, "ctx", None),
                               (ast.Store, ast.Del)):
                name = ImportResolver.dotted(sub)
                if name:
                    yield name


# ---------------------------------------------------------------------------
# shard-rebuild-dominance
# ---------------------------------------------------------------------------

#: functions that CUT a worker-local chunk out of a full buffer — their
#: results are only valid shard-wide (matched on the dotted name's last
#: segment so ``update_sharding.slice_chunk`` and a bare import both hit)
_SHARD_PRODUCERS = {"slice_chunk", "shard_tree"}
#: functions that REBUILD the full buffer from every worker's chunk —
#: binding through one of these cleanses the result
_SHARD_REBUILDS = {"all_gather_chunks", "unshard_tree", "all_gather"}
#: the schema's own producer helpers: returning a chunk is their JOB
_EXEMPT_FN = re.compile(r"^(shard|reshard|slice|chunk)_")


def _last_segment(func: ast.AST) -> Optional[str]:
    name = ImportResolver.dotted(func)
    return name.rsplit(".", 1)[-1] if name else None


@register
class ShardRebuildDominanceChecker(Checker):
    name = "shard-rebuild-dominance"
    description = ("a worker-local shard (slice_chunk/shard_tree result) "
                   "escaping a function without its allgather rebuild "
                   "dominating the return")
    needs_engine = False

    def check_file(self, sf: SourceFile):
        findings: List[Finding] = []
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if _EXEMPT_FN.match(fn.name):
                continue
            self._scan(sf, fn, fn.body, {}, findings, top=True)
        return findings

    def _scan(self, sf, fn, stmts, tainted: Dict[str, int],
              findings: List[Finding], top: bool) -> None:
        """Linear scan; ``tainted`` maps name → producer line.  Nested
        control-flow bodies scan with ``top=False``: taint they ADD is
        real (it may reach the return), but a rebuild there does NOT
        clear — it doesn't dominate the paths that skip the branch."""
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue        # nested defs are scanned as their own fns
            if isinstance(st, ast.Return) and st.value is not None:
                hit = self._expr_taint(st.value, tainted)
                if hit is not None:
                    name, line = hit
                    findings.append(Finding(
                        self.name, sf.path, st.lineno, st.col_offset,
                        f"`{name}` holds a worker-local shard (produced "
                        f"on line {line}) escaping `{fn.name}` without "
                        "its allgather rebuild (all_gather_chunks/"
                        "unshard_tree must dominate the return)"))
                continue
            if isinstance(st, (ast.If, ast.For, ast.AsyncFor, ast.While,
                               ast.Try, ast.With, ast.AsyncWith)):
                for fieldname in ("body", "orelse", "finalbody"):
                    sub = getattr(st, fieldname, None)
                    if sub:
                        self._scan(sf, fn, sub, tainted, findings,
                                   top=False)
                for h in getattr(st, "handlers", []):
                    self._scan(sf, fn, h.body, tainted, findings,
                               top=False)
                continue
            if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = st.value
                if value is None:
                    continue
                hit = self._expr_taint(value, tainted)
                targets = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                names = [n for t in targets
                         for n in self._target_names(t)]
                if hit is not None:
                    for n in names:
                        tainted[n] = hit[1]
                elif top:
                    # a clean rebind cleanses — but only here at the
                    # function's top level, where it dominates the return
                    for n in names:
                        tainted.pop(n, None)

    def _expr_taint(self, node, tainted: Dict[str, int]
                    ) -> Optional[Tuple[str, int]]:
        """(name, producer line) when the expression carries a shard:
        a producer call, a tainted name, or either propagated through
        arithmetic/containers/subscripts.  Arbitrary calls STOP taint —
        their result is a new value (the inner optimizer's elementwise
        update of a chunk is the schema's own business)."""
        if isinstance(node, ast.Call):
            last = _last_segment(node.func)
            if last in _SHARD_REBUILDS:
                return None
            if last in _SHARD_PRODUCERS:
                return (f"{last}(...)", node.lineno)
            return None
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = ImportResolver.dotted(node)
            if name in tainted:
                return (name, tainted[name])
            return None
        if isinstance(node, ast.BinOp):
            return (self._expr_taint(node.left, tainted)
                    or self._expr_taint(node.right, tainted))
        if isinstance(node, ast.UnaryOp):
            return self._expr_taint(node.operand, tainted)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                hit = self._expr_taint(e, tainted)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.Dict):
            for v in node.values:
                if v is not None:
                    hit = self._expr_taint(v, tainted)
                    if hit:
                        return hit
            return None
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self._expr_taint(node.value, tainted)
        if isinstance(node, ast.IfExp):
            return (self._expr_taint(node.body, tainted)
                    or self._expr_taint(node.orelse, tainted))
        return None

    @staticmethod
    def _target_names(t) -> List[str]:
        if isinstance(t, (ast.Tuple, ast.List)):
            return [n for e in t.elts
                    for n in ShardRebuildDominanceChecker._target_names(e)]
        if isinstance(t, ast.Starred):
            return ShardRebuildDominanceChecker._target_names(t.value)
        name = ImportResolver.dotted(t)
        return [name] if name else []

