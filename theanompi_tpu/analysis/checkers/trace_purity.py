"""trace-purity: no host-side leaks inside functions that get traced.

The invariant (docs/design.md §12): a function that flows into
``jax.jit`` / ``lax.scan`` / ``lax.cond`` / ``shard_map`` executes at
TRACE time — a ``time.time()`` there stamps the compile, not the step;
``np.random`` draws freeze one sample into the executable; ``print``
fires once per compile (or not at all on a cache hit); ``.item()`` /
``jax.device_get`` force a device sync mid-trace; and a Python ``if`` on
a tracer either fails to trace or, worse, specializes on one concrete
value.  All of these are the silent-throughput/correctness bug class
the Theano-MPI and pjit-scaling papers attribute regressions to.

Seeding: every function object passed (positionally or by keyword)
to a trace wrapper is traced — ``per_worker`` into ``shard_map``,
``body`` into ``lax.scan``, ``self.exchange_body`` into the standalone
collective (``steps.py`` / ``exchanger.py`` / ``model_base.py`` entry
points all match this shape) — plus, since the whole-program engine
(``analysis/engine.py``), TRANSITIVELY every function they can reach
through the repo-wide call graph: same-file calls, imported module
functions, ``self.<method>`` through the class hierarchy including
subclass overrides, and unique-family method names (the
``exchange_body`` rule).  A host clock two modules away from the scan
body is now visible.

The Python-``if``-on-tracer check is restricted to functions passed to
``lax.scan``-family primitives, whose arguments are tracers BY
CONSTRUCTION (jit/shard_map args can be static); there it flags
``if``/``while`` tests that read a parameter name.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, SourceFile, register
from ..engine import FuncRecord, ProgramIndex, body_walk

# Wrappers whose function arguments get traced.
TRACE_WRAPPERS = {
    "jax.jit",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.vmap",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "theanompi_tpu.jax_compat.shard_map",
    "jax.lax.scan",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.map",
    "jax.lax.associative_scan",
}

# Subset whose function arguments receive TRACERS by construction —
# a Python `if` on their parameters cannot be a static-config branch.
TRACER_ARG_WRAPPERS = {
    "jax.lax.scan", "jax.lax.cond", "jax.lax.switch",
    "jax.lax.while_loop", "jax.lax.fori_loop", "jax.lax.map",
    "jax.lax.associative_scan",
}


def _func_params(fn) -> Set[str]:
    a = fn.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


@register
class TracePurityChecker(Checker):
    name = "trace-purity"
    description = ("host clocks, numpy RNG, print, .item()/device_get, "
                   "and Python `if` on tracer args inside traced "
                   "functions — closed over the whole-program call graph")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        seeds: List[FuncRecord] = []
        tracer_args: Set[int] = set()
        for sf in index.files:
            self._seed_file(index, sf, seeds, tracer_args)

        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()

        def emit(rec: FuncRecord, node, msg):
            key = (rec.sf.path, node.lineno, msg)
            if key not in seen:
                seen.add(key)
                findings.append(Finding(self.name, rec.sf.path,
                                        node.lineno, node.col_offset, msg))

        for rec in index.reachable(seeds):
            self._scan_record(index, rec, id(rec.node) in tracer_args,
                              emit)
        return findings

    # -- seed discovery (per file, as the trace-wrapper call sites are
    #    lexical) ----------------------------------------------------------

    def _seed_file(self, index: ProgramIndex, sf: SourceFile,
                   seeds: List[FuncRecord], tracer_args: Set[int]) -> None:
        idx = index.file_index[sf.path]
        resolver = sf.resolver

        def add(node: ast.AST, scan_like: bool) -> None:
            rec = index.record_for(node)
            if rec is None:
                return
            seeds.append(rec)
            if scan_like:
                tracer_args.add(id(node))

        def mark(node, scan_like: bool, from_func) -> None:
            """Mark function refs found in a trace-wrapper argument."""
            for sub in ast.walk(node):
                targets: List[ast.AST] = []
                if isinstance(sub, ast.Lambda):
                    targets = [sub]
                elif isinstance(sub, (ast.Name, ast.Attribute)):
                    targets = [t.node for t in index.resolve_call(
                        sf, sub, from_func)]
                for t in targets:
                    add(t, scan_like)

        def decorator_traces(dec) -> bool:
            """``@jax.jit``, ``@jax.jit(...)``, and
            ``@functools.partial(jax.jit, ...)`` all trace the function
            they decorate."""
            if resolver.resolve(dec) in TRACE_WRAPPERS:
                return True
            if isinstance(dec, ast.Call):
                if resolver.resolve(dec.func) in TRACE_WRAPPERS:
                    return True
                if resolver.resolve(dec.func) == "functools.partial" \
                        and dec.args \
                        and resolver.resolve(dec.args[0]) in \
                        TRACE_WRAPPERS:
                    return True
            return False

        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(decorator_traces(d) for d in node.decorator_list):
                    add(node, False)
                continue
            if not isinstance(node, ast.Call):
                continue
            resolved = resolver.resolve(node.func)
            if resolved not in TRACE_WRAPPERS:
                continue
            scan_like = resolved in TRACER_ARG_WRAPPERS
            # keywords too (`lax.scan(f=body, ...)`, `jax.jit(fun=...)`)
            # — mark() only marks names that resolve to function DEFS,
            # so spec/mesh kwargs stay invisible
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                mark(arg, scan_like, idx.enclosing.get(id(node.func)))

    # -- host-leak scan of one traced function -----------------------------

    def _scan_record(self, index: ProgramIndex, rec: FuncRecord,
                     check_ifs: bool, emit) -> None:
        sf = rec.sf
        idx = index.file_index[sf.path]
        resolver = sf.resolver
        fname = rec.name
        params = _func_params(rec.node)

        # the engine summary carries clocks / numpy RNG / device_get
        for node, what in index.summary(rec).host_calls:
            if "host clock" in what:
                emit(rec, node, f"{what} inside traced function "
                                f"`{fname}`")
            elif "host RNG" in what:
                emit(rec, node, f"{what} inside traced function "
                                f"`{fname}` (freezes one draw into the "
                                "compiled program)")
            else:
                emit(rec, node, f"{what} inside traced function "
                                f"`{fname}` (host sync mid-trace)")

        for sub in body_walk(rec.node):
            if isinstance(sub, ast.Call):
                if isinstance(sub.func, ast.Name) and \
                        sub.func.id in ("print", "breakpoint", "input") \
                        and not idx.lookup(sub.func.id, rec.node):
                    emit(rec, sub, f"host `{sub.func.id}()` inside "
                                   f"traced function `{fname}` (fires "
                                   "at trace time, not per step)")
                elif isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "item" and not sub.args \
                        and not sub.keywords:
                    emit(rec, sub, f"`.item()` inside traced function "
                                   f"`{fname}` (host sync mid-trace)")
            elif check_ifs and isinstance(sub, (ast.If, ast.While)):
                hit = self._test_param(sub.test, params)
                if hit:
                    kind = "while" if isinstance(sub, ast.While) else "if"
                    emit(rec, sub, f"Python `{kind}` on tracer-typed "
                                   f"name `{hit}` inside `{fname}` "
                                   "(args of scan/cond bodies are "
                                   "tracers; use lax.cond/jnp.where)")

    @staticmethod
    def _test_param(test: ast.AST, params: Set[str]) -> Optional[str]:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Name) and sub.id in params and \
                    isinstance(sub.ctx, ast.Load):
                return sub.id
        return None
