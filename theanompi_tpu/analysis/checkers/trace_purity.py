"""trace-purity: no host-side leaks inside functions that get traced.

The invariant (docs/design.md §12): a function that flows into
``jax.jit`` / ``lax.scan`` / ``lax.cond`` / ``shard_map`` executes at
TRACE time — a ``time.time()`` there stamps the compile, not the step;
``np.random`` draws freeze one sample into the executable; ``print``
fires once per compile (or not at all on a cache hit); ``.item()`` /
``jax.device_get`` force a device sync mid-trace; and a Python ``if`` on
a tracer either fails to trace or, worse, specializes on one concrete
value.  All of these are the silent-throughput/correctness bug class
the Theano-MPI and pjit-scaling papers attribute regressions to.

Seeding: within each file, every function object passed (positionally)
to a trace wrapper is traced — ``per_worker`` into ``shard_map``,
``body`` into ``lax.scan``, ``self.exchange_body`` into the standalone
collective (``steps.py`` / ``exchanger.py`` / ``model_base.py`` entry
points all match this shape) — plus, transitively, every same-file
function they call by name (module-level, enclosing-local, or
``self.<method>``: all same-named methods in the file, covering
subclass overrides like the rules' ``exchange_body``).

The Python-``if``-on-tracer check is restricted to functions passed to
``lax.scan``-family primitives, whose arguments are tracers BY
CONSTRUCTION (jit/shard_map args can be static); there it flags
``if``/``while`` tests that read a parameter name.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, SourceFile, register

# Wrappers whose (positional) function arguments get traced.
TRACE_WRAPPERS = {
    "jax.jit",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.vmap",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "theanompi_tpu.jax_compat.shard_map",
    "jax.lax.scan",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.map",
    "jax.lax.associative_scan",
}

# Subset whose function arguments receive TRACERS by construction —
# a Python `if` on their parameters cannot be a static-config branch.
TRACER_ARG_WRAPPERS = {
    "jax.lax.scan", "jax.lax.cond", "jax.lax.switch",
    "jax.lax.while_loop", "jax.lax.fori_loop", "jax.lax.map",
    "jax.lax.associative_scan",
}

HOST_CLOCKS = {"time.time", "time.perf_counter", "time.monotonic",
               "time.process_time", "time.sleep"}
SYNC_CALLS = {"jax.device_get"}

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _func_params(fn) -> Set[str]:
    a = fn.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


class _Index:
    """Per-file function index: defs by enclosing scope, methods by name."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        # id(scope-node-or-None) -> {name: [def nodes]}
        self.by_scope: Dict[Optional[int], Dict[str, List[ast.AST]]] = {}
        # method name -> [def nodes] across every class in the file
        self.methods: Dict[str, List[ast.AST]] = {}
        # def node id -> enclosing function node (for local lookup chains)
        self.parent_func: Dict[int, Optional[ast.AST]] = {}
        self._walk(sf.tree, None, None)

    def _walk(self, node, func: Optional[ast.AST], cls: Optional[ast.AST]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = self.by_scope.setdefault(
                    id(func) if func else None, {})
                scope.setdefault(child.name, []).append(child)
                if cls is not None and func is None or \
                        (cls is not None and isinstance(node, ast.ClassDef)):
                    self.methods.setdefault(child.name, []).append(child)
                self.parent_func[id(child)] = func
                self._walk(child, child, None)
            elif isinstance(child, ast.ClassDef):
                self._walk(child, func, child)
            elif isinstance(child, ast.Lambda):
                self.parent_func[id(child)] = func
                self._walk(child, child, None)
            else:
                self._walk(child, func, cls)

    def lookup(self, name: str, from_func: Optional[ast.AST]
               ) -> List[ast.AST]:
        """Defs named ``name`` visible from ``from_func``: its locals,
        then enclosing functions', then module level."""
        seen: List[ast.AST] = []
        f = from_func
        while True:
            scope = self.by_scope.get(id(f) if f else None, {})
            if name in scope:
                seen.extend(scope[name])
                return seen
            if f is None:
                return seen
            f = self.parent_func.get(id(f))


@register
class TracePurityChecker(Checker):
    name = "trace-purity"
    description = ("host clocks, numpy RNG, print, .item()/device_get, "
                   "and Python `if` on tracer args inside traced functions")

    def check_file(self, sf: SourceFile):
        idx = _Index(sf)
        resolver = sf.resolver

        # ---- seed: functions passed positionally to trace wrappers ----
        traced: Dict[int, ast.AST] = {}           # id -> def node
        tracer_args: Set[int] = set()             # ids with tracer params
        # enclosing function of every node (for name lookup at call sites)
        encl: Dict[int, Optional[ast.AST]] = {}

        def record_enclosing(node, func):
            encl[id(node)] = func
            for child in ast.iter_child_nodes(node):
                record_enclosing(
                    child, child if isinstance(child, _FuncNode) else func)

        record_enclosing(sf.tree, None)

        def mark(node, scan_like: bool, from_func):
            """Mark function refs found in a trace-wrapper argument."""
            for sub in ast.walk(node):
                targets: List[ast.AST] = []
                if isinstance(sub, ast.Lambda):
                    targets = [sub]
                elif isinstance(sub, ast.Name):
                    targets = idx.lookup(sub.id, from_func)
                elif isinstance(sub, ast.Attribute) and \
                        isinstance(sub.value, ast.Name) and \
                        sub.value.id in ("self", "cls"):
                    targets = idx.methods.get(sub.attr, [])
                for t in targets:
                    if id(t) not in traced:
                        traced[id(t)] = t
                    if scan_like:
                        tracer_args.add(id(t))

        def decorator_traces(dec) -> bool:
            """``@jax.jit``, ``@jax.jit(...)``, and
            ``@functools.partial(jax.jit, ...)`` all trace the function
            they decorate."""
            if resolver.resolve(dec) in TRACE_WRAPPERS:
                return True
            if isinstance(dec, ast.Call):
                if resolver.resolve(dec.func) in TRACE_WRAPPERS:
                    return True
                if resolver.resolve(dec.func) == "functools.partial" \
                        and dec.args \
                        and resolver.resolve(dec.args[0]) in \
                        TRACE_WRAPPERS:
                    return True
            return False

        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(decorator_traces(d) for d in node.decorator_list):
                    traced.setdefault(id(node), node)
                continue
            if not isinstance(node, ast.Call):
                continue
            resolved = resolver.resolve(node.func)
            if resolved not in TRACE_WRAPPERS:
                continue
            scan_like = resolved in TRACER_ARG_WRAPPERS
            # keywords too (`lax.scan(f=body, ...)`, `jax.jit(fun=...)`)
            # — mark() only marks names that resolve to function DEFS,
            # so spec/mesh kwargs stay invisible
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                mark(arg, scan_like, encl.get(id(node.func)))

        # ---- transitive closure: same-file calls from traced functions ----
        changed = True
        while changed:
            changed = False
            for fid, fn in list(traced.items()):
                for sub in self._body_walk(fn):
                    if not isinstance(sub, ast.Call):
                        continue
                    targets: List[ast.AST] = []
                    if isinstance(sub.func, ast.Name):
                        targets = idx.lookup(sub.func.id, fn)
                    elif isinstance(sub.func, ast.Attribute) and \
                            isinstance(sub.func.value, ast.Name) and \
                            sub.func.value.id in ("self", "cls"):
                        targets = idx.methods.get(sub.func.attr, [])
                    for t in targets:
                        if id(t) not in traced:
                            traced[id(t)] = t
                            changed = True

        # ---- walk each traced function for host leaks ----
        findings: List[Finding] = []
        seen_lines: Set[Tuple[int, str]] = set()

        def emit(node, msg):
            key = (node.lineno, msg)
            if key not in seen_lines:
                seen_lines.add(key)
                findings.append(Finding(self.name, sf.path, node.lineno,
                                        node.col_offset, msg))

        for fid, fn in traced.items():
            fname = getattr(fn, "name", "<lambda>")
            params = _func_params(fn)
            check_ifs = fid in tracer_args
            for sub in self._body_walk(fn):
                if isinstance(sub, ast.Call):
                    resolved = resolver.resolve(sub.func)
                    if resolved in HOST_CLOCKS:
                        emit(sub, f"host clock `{resolved}()` inside "
                                  f"traced function `{fname}`")
                    elif resolved and resolved.startswith("numpy.random."):
                        emit(sub, f"host RNG `{resolved}()` inside traced "
                                  f"function `{fname}` (freezes one draw "
                                  "into the compiled program)")
                    elif resolved in SYNC_CALLS:
                        emit(sub, f"`{resolved}()` inside traced function "
                                  f"`{fname}` (host sync mid-trace)")
                    elif isinstance(sub.func, ast.Name) and \
                            sub.func.id in ("print", "breakpoint", "input") \
                            and not idx.lookup(sub.func.id, fn):
                        emit(sub, f"host `{sub.func.id}()` inside traced "
                                  f"function `{fname}` (fires at trace "
                                  "time, not per step)")
                    elif isinstance(sub.func, ast.Attribute) and \
                            sub.func.attr == "item" and not sub.args \
                            and not sub.keywords:
                        emit(sub, f"`.item()` inside traced function "
                                  f"`{fname}` (host sync mid-trace)")
                elif check_ifs and isinstance(sub, (ast.If, ast.While)):
                    hit = self._test_param(sub.test, params)
                    if hit:
                        kind = "while" if isinstance(sub, ast.While) \
                            else "if"
                        emit(sub, f"Python `{kind}` on tracer-typed name "
                                  f"`{hit}` inside `{fname}` (args of "
                                  "scan/cond bodies are tracers; use "
                                  "lax.cond/jnp.where)")
        return findings

    @staticmethod
    def _test_param(test: ast.AST, params: Set[str]) -> Optional[str]:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Name) and sub.id in params and \
                    isinstance(sub.ctx, ast.Load):
                return sub.id
        return None

    @staticmethod
    def _body_walk(fn):
        """Walk a function's body, NOT descending into nested
        FunctionDefs (traced separately if reachable) but following
        inline lambdas (they run at trace time via tree.map etc.)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))
