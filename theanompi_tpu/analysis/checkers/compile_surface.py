"""Compile-surface discipline: cache-key completeness, retrace hazards,
mixed-precision dtype flow (docs/design.md §26).

The AOT/prewarm strategy (§10) rests on a contract every PR since 3 has
re-pinned by hand: a config knob that shapes a traced program must be
stamped into ``compile_cache.key_extra`` — stamped *only-when-on* so
pre-existing cache keys stay byte-stable — and trace-reachable code must
not silently recompile per step or silently change numerics.  Three
checkers make the contract machine-checked:

``cache-key``
    Taints config reads (``config["x"]`` / ``self.config.get("x")`` /
    ``parse_kv`` outputs) in functions reachable from the AOT surfaces
    (:data:`AOT_SURFACES`) and requires any knob that flows into a
    trace-shaping slot (scan lengths, ``lax.cond`` predicates, schedule
    builders, bucket planners, PartitionSpec construction, jit
    donation/static signatures — ``engine.TRACE_SHAPE_SLOTS`` /
    ``TRACE_PRED_SLOTS``) to be covered by a ``key_extra`` stamp.
    Coverage is the union of the knobs lexically read inside
    ``key_extra`` itself and :data:`STAMP_KNOBS`, this checker's
    pure-literal stamp→knobs registry; the registry is cross-validated
    against the statically-extracted stamp vocabulary (stale or missing
    entries are findings), and every stamp except ``fn`` must sit under
    a guard (the only-when-on rule).  Deliberate exemptions carry
    ``# tpulint: disable=cache-key`` at the read site.

``retrace-hazard``
    Call shapes that silently recompile per step: a fresh
    ``lambda``/``functools.partial`` at a ``jax.jit`` boundary (jit
    caches by function identity), ``jax.jit`` invoked inside a loop, a
    jit-boundary parameter spent in a shape-static slot without
    ``static_argnums`` (concretization-error-or-per-value-retrace bait),
    host values (clocks, ``os.environ``, host RNG) feeding shape
    arithmetic in trace-reachable code, and ``.lower()`` on a program
    that already came out of ``CompileCache.get_or_compile`` (the PR 3
    regression class).

``dtype-flow``
    Low-precision wire numerics: a collective whose operand is
    statically cast to bf16/f16 must re-upcast before any accumulate
    (``+``/``sum``/``mean``); a wire cast applied to the packed vector
    before bucketing breaks the §19 per-bucket contract; and any
    deliberate non-bit-exact rounding (a direct
    ``.astype(a).astype(b)`` round-trip) must be registered in the
    module's pure-literal ``NONBITEXACT = {"Class.method": "reason"}``
    registry (the ``PALLAS_ORACLES`` pattern) — unregistered round-trips
    and stale registry entries are both findings.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import (Checker, Finding, ImportResolver, SourceFile,
                    register)
from ..engine import (HOST_CLOCKS, LOW_PRECISION_DTYPES, ProgramIndex,
                      bare_names, body_walk, collective_name, config_knob,
                      static_dtype)

COMPILE_CACHE_PATH = "theanompi_tpu/utils/compile_cache.py"

#: Function simple names whose bodies build traced programs — the taint
#: seeds.  Matched by simple name so single-file fixture runs resolve
#: the same way the repo tree does.
AOT_SURFACES = (
    "aot_train_program", "_aot_from_cache", "compile_iter_fns",
    "build_train_step", "build_val_step", "build_schedule", "plan_tree",
    "exchange_body",
)

#: Stamps ``key_extra`` writes unconditionally by design.  Everything
#: else must be guarded by its knob's truthiness (only-when-on): a new
#: stamp that fires for knob-less configs would churn every pre-existing
#: cache key (the §19/§22–§25 byte-stability rule).
UNGUARDED_STAMPS_OK = ("fn",)

#: Pure-literal coverage registry: stamp name -> the config knobs it
#: covers.  Knobs read lexically inside ``key_extra`` itself (e.g.
#: ``numerics``, ``update_sharding``) are extracted statically; this map
#: carries the coverage the extraction cannot see — model/exchanger
#: attributes that mirror config knobs set elsewhere.  The cache-key
#: checker cross-validates the keys against the extracted stamp
#: vocabulary, and the schema-drift live probe pins both against the
#: keys a real ``key_extra`` run stamps.
STAMP_KNOBS = {
    "fn": (),
    "model": (),
    "n_subb": ("n_subb",),
    "pp_interleave": ("pp_interleave", "pp", "pp_microbatches",
                      "n_layer"),
    "numerics": ("numerics", "numerics_every"),
    "ushard": ("update_sharding", "ushard_min_bytes"),
    "spc": ("steps_per_call",),
    "rule": ("exch_strategy", "exch_mode", "sync_freq",
             "exchange_freq"),
    "bucket_bytes": ("bucket_bytes",),
    "no_pallas": (),
}

#: One-line meanings, reused by ``scripts/explain_program.py --diff`` to
#: name the knob that produced a cache-key split.
STAMP_MEANING = {
    "fn": "program family (train/val/exchange/zero_shadow/fsdp_val)",
    "model": "model class",
    "n_subb": "gradient-accumulation sub-batches per step",
    "pp_interleave": "virtual pipeline stages per worker",
    "numerics": "numerics health-plane cadence",
    "ushard": "update-plane sharding min bucket bytes",
    "spc": "fused steps per compiled call",
    "rule": "exchange rule (Type:mode:strategy:freq)",
    "bucket_bytes": "wire bucket size in bytes",
    "no_pallas": "Pallas kernels disabled (jnp fallbacks traced)",
}

NONBITEXACT_NAME = "NONBITEXACT"

_SHARD_MAPS = ("jax.shard_map", "jax.experimental.shard_map.shard_map",
               "theanompi_tpu.jax_compat.shard_map")


# ---------------------------------------------------------------------------
# key_extra static extraction (shared with the schema-drift live probe)
# ---------------------------------------------------------------------------

def key_extra_def(sf: SourceFile) -> Optional[ast.FunctionDef]:
    """The module-level ``key_extra`` definition in one file, or None."""
    for node in sf.tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == "key_extra":
            return node
    return None


def key_extra_vocabulary(sf: SourceFile):
    """Statically extract ``key_extra``'s stamp vocabulary.

    Returns ``(stamps, knobs, problems)``: ``stamps`` maps each
    ``extra["name"] = …`` stamp to ``(line, guarded)`` (guarded = every
    assignment of it sits under an ``if``), ``knobs`` is every config
    knob read lexically inside the function, ``problems`` is a list of
    ``(line, message)`` for non-literal stamp keys (an unextractable
    vocabulary would let the whole contract go stale silently)."""
    fn = key_extra_def(sf)
    if fn is None:
        return {}, set(), []
    stamps: Dict[str, Tuple[int, bool]] = {}
    problems: List[Tuple[int, str]] = []
    knobs: Set[str] = set()

    def add(name: str, line: int, guarded: bool) -> None:
        if name in stamps:
            old_line, old_g = stamps[name]
            stamps[name] = (old_line, old_g and guarded)
        else:
            stamps[name] = (line, guarded)

    def visit(node: ast.AST, guarded: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_guarded = guarded or isinstance(node, ast.If)
            if isinstance(child, (ast.Assign, ast.AnnAssign)):
                targets = child.targets if isinstance(child, ast.Assign) \
                    else [child.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "extra":
                        if isinstance(t.slice, ast.Constant) and \
                                isinstance(t.slice.value, str):
                            add(t.slice.value, child.lineno,
                                child_guarded)
                        else:
                            problems.append((
                                child.lineno,
                                "non-literal key_extra stamp key — the "
                                "stamp vocabulary must be statically "
                                "extractable (docs/design.md §26)"))
                    elif isinstance(t, ast.Name) and t.id == "extra" \
                            and isinstance(child.value, ast.Dict):
                        # the initializer: extra = {"fn": str(fn), ...}
                        for k in child.value.keys:
                            if isinstance(k, ast.Constant) and \
                                    isinstance(k.value, str):
                                add(k.value, child.lineno, child_guarded)
                            else:
                                problems.append((
                                    child.lineno,
                                    "non-literal key_extra stamp key — "
                                    "the stamp vocabulary must be "
                                    "statically extractable "
                                    "(docs/design.md §26)"))
            visit(child, child_guarded)

    visit(fn, False)
    for node in ast.walk(fn):
        k = config_knob(node)
        if k is not None:
            knobs.add(k)
    return stamps, knobs, problems


# ---------------------------------------------------------------------------
# cache-key completeness
# ---------------------------------------------------------------------------

def _parse_kv_locals(fn_node: ast.AST) -> Set[str]:
    """Local names bound from ``parse_kv(...)`` — config dicts too."""
    out: Set[str] = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign) and \
                isinstance(sub.value, ast.Call):
            f = sub.value.func
            fname = f.attr if isinstance(f, ast.Attribute) else \
                getattr(f, "id", None)
            if fname == "parse_kv":
                out.update(t.id for t in sub.targets
                           if isinstance(t, ast.Name))
    return out


def tainted_knob_reads(rec, index: ProgramIndex):
    """``(line, col, knob, why)`` for every config-knob read in ``rec``
    (nested defs included — closure flows) whose value reaches a
    trace-shaping slot, directly or through a one-assignment local."""
    cfg_locals = _parse_kv_locals(rec.node)
    reads: Dict[int, Tuple[str, int, int]] = {}
    for sub in ast.walk(rec.node):
        knob = config_knob(sub, cfg_locals)
        if knob is not None:
            reads[id(sub)] = (knob, sub.lineno, sub.col_offset)
    if not reads:
        return []
    var_knobs: Dict[str, List[Tuple[str, int, int]]] = {}
    for sub in ast.walk(rec.node):
        if not isinstance(sub, ast.Assign):
            continue
        contained = [reads[id(n)] for n in ast.walk(sub.value)
                     if id(n) in reads]
        if contained:
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    var_knobs.setdefault(t.id, []).extend(contained)
    out = []
    for expr, why in index.shaping_use_sites(rec, preds=True, deep=True):
        for n in ast.walk(expr):
            if id(n) in reads:
                knob, line, col = reads[id(n)]
                out.append((line, col, knob, why))
        for nm in bare_names(expr):
            for knob, line, col in var_knobs.get(nm.id, ()):
                out.append((line, col, knob, why))
    return out


@register
class CacheKeyChecker(Checker):
    name = "cache-key"
    description = ("config knobs that shape a traced program reachable "
                   "from an AOT surface must reach a "
                   "compile_cache.key_extra stamp, guarded only-when-on")
    needs_engine = True
    disk_scoped = (COMPILE_CACHE_PATH,)

    def check_program(self, index: ProgramIndex) -> Iterable[Finding]:
        findings: List[Finding] = []
        sf = index.by_path.get(COMPILE_CACHE_PATH)
        if sf is None:
            # --diff partial runs: the canonical vocabulary still gates
            # the taint pass, so load it from disk (keyed into the
            # result cache via ``disk_scoped``)
            root = index.files[0].root if index.files else "."
            try:
                sf = SourceFile(root, COMPILE_CACHE_PATH)
            except (OSError, SyntaxError, ValueError):
                sf = None
        if sf is None or key_extra_def(sf) is None:
            # fixture trees: any in-scope module-level key_extra
            sf = next((c for c in index.files
                       if key_extra_def(c) is not None), None)

        covered: Set[str] = set()
        for ks in STAMP_KNOBS.values():
            covered.update(ks)
        if sf is not None:
            stamps, knobs, problems = key_extra_vocabulary(sf)
            covered |= knobs
            for line, msg in problems:
                findings.append(Finding(self.name, sf.path, line, 0, msg))
            for stamp in sorted(stamps):
                line, guarded = stamps[stamp]
                if not guarded and stamp not in UNGUARDED_STAMPS_OK:
                    findings.append(Finding(
                        self.name, sf.path, line, 0,
                        f"key_extra stamp '{stamp}' is unconditional — "
                        f"stamp only-when-on (guard with the knob's "
                        f"truthiness) so knob-less configs keep "
                        f"byte-stable cache keys"))
            if sf.path == COMPILE_CACHE_PATH:
                # the coverage registry must track the real vocabulary
                for stamp in sorted(set(stamps) - set(STAMP_KNOBS)):
                    findings.append(Finding(
                        self.name, sf.path, stamps[stamp][0], 0,
                        f"key_extra stamp '{stamp}' has no STAMP_KNOBS "
                        f"entry in analysis/checkers/compile_surface.py "
                        f"— declare which config knobs it covers"))
                fn = key_extra_def(sf)
                for stamp in sorted(set(STAMP_KNOBS) - set(stamps)):
                    findings.append(Finding(
                        self.name, sf.path, fn.lineno, 0,
                        f"stale STAMP_KNOBS entry '{stamp}' in "
                        f"analysis/checkers/compile_surface.py: "
                        f"key_extra stamps no such key"))

        seeds = [rec for rec in index.records.values()
                 if rec.name in AOT_SURFACES]
        seen: Set[Tuple[str, str]] = set()
        for rec in index.reachable(seeds):
            if isinstance(rec.node, ast.Lambda):
                continue
            fidx = index.file_index[rec.sf.path]
            if fidx.parent_func.get(id(rec.node)) is not None:
                continue   # nested defs: analyzed with their parent's
                #            scope so closure-variable taint is visible
            for line, col, knob, why in tainted_knob_reads(rec, index):
                if knob in covered:
                    continue
                key = (rec.sf.path, knob)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    self.name, rec.sf.path, line, col,
                    f"config knob '{knob}' shapes the traced program "
                    f"({why} in `{rec.name}`) but never reaches a "
                    f"compile_cache.key_extra stamp — an AOT cache hit "
                    f"could reuse a stale executable across '{knob}' "
                    f"values; stamp it only-when-on or justify with "
                    f"`# tpulint: disable=cache-key`"))
        return findings


# ---------------------------------------------------------------------------
# retrace hazards
# ---------------------------------------------------------------------------

def _jit_static_names(call: ast.Call, params: List[str]) -> Set[str]:
    """Parameter names covered by static_argnums/static_argnames."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        vals = kw.value.elts if isinstance(
            kw.value, (ast.Tuple, ast.List)) else [kw.value]
        for v in vals:
            if not isinstance(v, ast.Constant):
                continue
            if isinstance(v.value, int) and v.value < len(params):
                out.add(params[v.value])
            elif isinstance(v.value, str):
                out.add(v.value)
    return out


def _host_value_desc(node: ast.AST, resolver: ImportResolver
                     ) -> Optional[str]:
    """A description when ``node`` produces a host value that varies
    across calls (clock, environment, host RNG), else None."""
    if isinstance(node, ast.Call):
        resolved = resolver.resolve(node.func)
        if resolved in HOST_CLOCKS:
            return f"`{resolved}()`"
        if resolved and resolved.startswith("numpy.random."):
            return f"`{resolved}()`"
    dotted = ImportResolver.dotted(node)
    if dotted and (dotted == "os.environ" or
                   dotted.startswith("os.environ.")):
        return "`os.environ`"
    return None


@register
class RetraceHazardChecker(Checker):
    name = "retrace-hazard"
    description = ("jit boundaries that silently recompile per step: "
                   "fresh lambda/partial identity, jit in a loop, "
                   "non-static shape params, host values in shape "
                   "arithmetic, .lower() on an installed Compiled")
    needs_engine = True

    def check_program(self, index: ProgramIndex) -> Iterable[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()

        def emit(path: str, node: ast.AST, msg: str) -> None:
            key = (path, node.lineno, msg)
            if key not in seen:
                seen.add(key)
                findings.append(Finding(self.name, path, node.lineno,
                                        node.col_offset, msg))

        boundaries: List[Tuple] = []   # (rec, static names, kind)
        for sf in index.files:
            # tests build throwaway jits (cache probes, identity
            # checks) on purpose — the contract binds the library
            if sf.path.startswith("tests/"):
                continue
            self._scan_file(index, sf, emit, boundaries)
        for rec, static_names, kind in boundaries:
            params = rec.params()
            for i in sorted(index.shaping_params(rec, preds=False)):
                p = params[i]
                if p in static_names:
                    continue
                emit(rec.sf.path, rec.node,
                     f"{kind} function `{rec.name}` spends parameter "
                     f"`{p}` in a shape-static slot (reshape/arange/"
                     f"scan length) — a traced value there is "
                     f"concretization-error-or-retrace bait; mark it "
                     f"static (and expect a recompile per distinct "
                     f"value) or derive it from aval shapes")
        # host values feeding shape arithmetic, over the trace-reachable
        # closure (AOT surfaces + jit boundaries)
        seeds = [rec for rec in index.records.values()
                 if rec.name in AOT_SURFACES]
        seeds += [rec for rec, _s, _k in boundaries]
        for rec in index.reachable(seeds):
            if isinstance(rec.node, ast.Lambda) or \
                    rec.sf.path.startswith("tests/"):
                continue
            fidx = index.file_index[rec.sf.path]
            if fidx.parent_func.get(id(rec.node)) is not None:
                continue
            resolver = rec.sf.resolver
            for expr, why in index.shaping_use_sites(rec, preds=False,
                                                     deep=True):
                for n in ast.walk(expr):
                    desc = _host_value_desc(n, resolver)
                    if desc is not None:
                        emit(rec.sf.path, n,
                             f"host value {desc} feeds shape arithmetic "
                             f"({why} in `{rec.name}`) — shapes derived "
                             f"from host state retrace whenever it "
                             f"drifts; hoist it to a build-time "
                             f"constant")
        return findings

    def _scan_file(self, index: ProgramIndex, sf: SourceFile, emit,
                   boundaries: List[Tuple]) -> None:
        resolver = sf.resolver
        compiled_names: Set[str] = set()

        fidx = index.file_index[sf.path]

        def note_boundary(fn_expr, call: Optional[ast.Call],
                          kind: str) -> None:
            if not isinstance(fn_expr, (ast.Name, ast.Attribute)):
                return
            enc = fidx.enclosing.get(id(fn_expr))
            for tgt in index.resolve_call(sf, fn_expr, enc):
                statics = _jit_static_names(call, tgt.params()) \
                    if call is not None else set()
                boundaries.append((tgt, statics, kind))

        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dres = resolver.resolve(dec)
                    dcall = dec if isinstance(dec, ast.Call) else None
                    if dcall is not None:
                        fres = resolver.resolve(dcall.func)
                        if fres == "jax.jit":
                            dres = "jax.jit"
                        elif fres == "functools.partial" and dcall.args \
                                and resolver.resolve(dcall.args[0]) == \
                                "jax.jit":
                            dres = "jax.jit"
                    if dres == "jax.jit":
                        rec = index.record_for(node)
                        if rec is not None:
                            statics = _jit_static_names(
                                dcall, rec.params()) if dcall else set()
                            boundaries.append((rec, statics,
                                               "jit-decorated"))
                continue
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Attribute) and \
                    node.value.func.attr == "get_or_compile":
                # the PR 3 regression class: re-lowering an installed
                # Compiled re-traces and re-compiles per call
                targets = list(node.targets)
                if len(targets) == 1 and \
                        isinstance(targets[0], ast.Tuple) and \
                        targets[0].elts:
                    targets = [targets[0].elts[0]]
                for t in targets:
                    if isinstance(t, ast.Name):
                        compiled_names.add(t.id)
                    elif isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        compiled_names.add(t.attr)
                continue
            if not isinstance(node, ast.Call):
                continue
            resolved = resolver.resolve(node.func)
            if resolved == "jax.jit" and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Lambda):
                    emit(sf.path, node,
                         "fresh lambda at a jax.jit boundary — jit "
                         "caches by function identity, so every call "
                         "of the enclosing code re-traces; hoist the "
                         "lambda to a def")
                elif isinstance(a0, ast.Call) and resolver.resolve(
                        a0.func) == "functools.partial":
                    emit(sf.path, node,
                         "functools.partial built inline at a jax.jit "
                         "boundary — each partial is a fresh identity, "
                         "defeating jit's cache; bind the partial once "
                         "and jit the bound name")
                else:
                    note_boundary(a0, node, "jitted")
            elif resolved in _SHARD_MAPS and node.args:
                note_boundary(node.args[0], None, "shard-mapped")
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "lower" and \
                    compiled_names:
                base = node.func.value
                attr = None
                if isinstance(base, ast.Name):
                    attr = base.id
                elif isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self":
                    attr = base.attr
                if attr in compiled_names:
                    emit(sf.path, node,
                         f"`.lower()` on `{attr}`, which already holds "
                         f"a CompileCache.get_or_compile result — "
                         f"re-lowering an installed Compiled re-traces "
                         f"and re-compiles per call (the PR 3 "
                         f"regression); lower once at AOT build time "
                         f"and reuse the executable")
        # jax.jit invoked inside a loop body: a new jitted callable (and
        # trace) per iteration
        def visit(node: ast.AST, in_loop: bool) -> None:
            for fname, val in ast.iter_fields(node):
                children = val if isinstance(val, list) else [val]
                for c in children:
                    if not isinstance(c, ast.AST):
                        continue
                    flag = in_loop
                    if isinstance(node, (ast.For, ast.AsyncFor,
                                         ast.While)) and \
                            fname in ("body", "orelse"):
                        flag = True
                    if isinstance(c, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                        flag = False   # def bodies run when called
                    if flag and isinstance(c, ast.Call) and \
                            resolver.resolve(c.func) == "jax.jit":
                        emit(sf.path, c,
                             "jax.jit called inside a loop — every "
                             "iteration builds a new jitted callable "
                             "and re-traces; hoist the jit out of the "
                             "loop")
                    visit(c, flag)

        visit(sf.tree, False)


# ---------------------------------------------------------------------------
# dtype flow
# ---------------------------------------------------------------------------

def _low_collective_dtype(call: ast.Call, resolver: ImportResolver
                          ) -> Optional[str]:
    """The statically-resolved low-precision dtype of a collective's
    operand, or None."""
    cname = collective_name(resolver.resolve(call.func))
    if cname is None or not call.args:
        return None
    for n in ast.walk(call.args[0]):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr == "astype" and n.args:
            dt = static_dtype(n.args[0], resolver)
            if dt in LOW_PRECISION_DTYPES:
                return dt
    return None


def _accumulate_desc(node: ast.AST, resolver: ImportResolver
                     ) -> Optional[str]:
    """A description when ``node`` is an accumulate context, else None."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return "`+`"
    if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
        return "`+=`"
    if isinstance(node, ast.Call):
        resolved = resolver.resolve(node.func)
        if resolved in ("jax.numpy.sum", "jax.numpy.mean",
                        "jax.numpy.add", "jax.numpy.cumsum"):
            return f"`{resolved.rsplit('.', 1)[-1]}`"
    return None


def nonbitexact_registry(sf: SourceFile):
    """``(entries, line, problem)`` for a module's ``NONBITEXACT``
    registry: the literal dict (or {}), the assignment line, and an
    error message when the value is not a pure ``{str: str}`` literal."""
    for node in sf.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == NONBITEXACT_NAME
                   for t in targets):
            continue
        try:
            val = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            val = None
        if not isinstance(val, dict) or not all(
                isinstance(k, str) and isinstance(v, str) and v.strip()
                for k, v in val.items()):
            return {}, node.lineno, (
                f"{NONBITEXACT_NAME} must be a pure literal "
                f"{{\"Class.method\": \"reason\"}} dict — computed "
                f"registries cannot be audited statically")
        return val, node.lineno, None
    return {}, 0, None


@register
class DtypeFlowChecker(Checker):
    name = "dtype-flow"
    description = ("bf16/f16 collective results must re-upcast before "
                   "accumulating; wire casts are per-bucket; deliberate "
                   "astype round-trips must be registered in "
                   "NONBITEXACT")

    def applies_to(self, path: str) -> bool:
        # tests mirror wire-rounding chains in their oracles; the
        # contract binds the library
        return not path.startswith("tests/")

    def check_file(self, sf: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        registry, reg_line, reg_problem = nonbitexact_registry(sf)
        if reg_problem:
            findings.append(Finding(self.name, sf.path, reg_line, 0,
                                    reg_problem))

        # enclosing "Class.method" / "func" site names for registry keys
        site_of: Dict[int, str] = {}

        def map_sites(node: ast.AST, site: Optional[str],
                      cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                child_site, child_cls = site, cls
                if isinstance(child, ast.ClassDef):
                    child_cls = child.name
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    if site is None:
                        child_site = f"{cls}.{child.name}" if cls \
                            else child.name
                site_of[id(child)] = child_site
                map_sites(child, child_site, child_cls)

        map_sites(sf.tree, None, None)

        chain_sites: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "astype" and \
                    isinstance(node.func.value, ast.Call) and \
                    isinstance(node.func.value.func, ast.Attribute) and \
                    node.func.value.func.attr == "astype":
                site = site_of.get(id(node)) or "<module>"
                chain_sites.add(site)
                if site not in registry:
                    findings.append(Finding(
                        self.name, sf.path, node.lineno, node.col_offset,
                        f"non-bit-exact astype round-trip in `{site}` — "
                        f"deliberate wire rounding/reassociation must "
                        f"be registered in this module's "
                        f"{NONBITEXACT_NAME} registry with a one-line "
                        f"reason (docs/design.md §26)"))
        for key in sorted(set(registry) - chain_sites):
            findings.append(Finding(
                self.name, sf.path, reg_line, 0,
                f"stale {NONBITEXACT_NAME} entry '{key}': no astype "
                f"round-trip remains at that site — drop the entry so "
                f"the registry keeps matching reality"))

        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(sf, node))
        return findings

    def _check_function(self, sf: SourceFile, fn: ast.AST):
        resolver = sf.resolver
        parents: Dict[int, ast.AST] = {}
        for sub in body_walk(fn):
            for c in ast.iter_child_nodes(sub):
                parents[id(c)] = sub
        for c in ast.iter_child_nodes(fn):
            parents.setdefault(id(c), fn)

        def accumulate_above(node: ast.AST) -> Optional[Tuple[ast.AST,
                                                              str]]:
            """First accumulate ancestor before an .astype re-wrap."""
            cur = node
            while True:
                p = parents.get(id(cur))
                if p is None:
                    return None
                if isinstance(p, ast.Attribute) and p.attr == "astype":
                    return None        # re-upcast wraps the value
                desc = _accumulate_desc(p, resolver)
                if desc is not None:
                    return p, desc
                cur = p

        low_vars: Dict[str, str] = {}   # name -> wire dtype
        upcast_vars: Set[str] = set()
        findings: List[Finding] = []
        for sub in body_walk(fn):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "astype" and \
                    isinstance(sub.func.value, ast.Name):
                upcast_vars.add(sub.func.value.id)
            if not isinstance(sub, ast.Call):
                continue
            dt = _low_collective_dtype(sub, resolver)
            if dt is None:
                continue
            hit = accumulate_above(sub)
            if hit is not None:
                node, desc = hit
                findings.append(Finding(
                    self.name, sf.path, sub.lineno, sub.col_offset,
                    f"{dt} collective result accumulated via {desc} "
                    f"without re-upcasting — low-precision "
                    f"accumulation compounds rounding error; "
                    f"`.astype()` back up immediately after the "
                    f"collective (the strategies.py pattern)"))
                continue
            p = parents.get(id(sub))
            if isinstance(p, ast.Assign):
                for t in p.targets:
                    if isinstance(t, ast.Name):
                        low_vars[t.id] = dt
        if low_vars:
            for sub in body_walk(fn):
                if not (isinstance(sub, ast.Name) and
                        isinstance(sub.ctx, ast.Load) and
                        sub.id in low_vars and
                        sub.id not in upcast_vars):
                    continue
                hit = accumulate_above(sub)
                if hit is not None:
                    node, desc = hit
                    findings.append(Finding(
                        self.name, sf.path, sub.lineno, sub.col_offset,
                        f"{low_vars[sub.id]} collective result "
                        f"`{sub.id}` accumulated via {desc} without "
                        f"re-upcasting — low-precision accumulation "
                        f"compounds rounding error; `.astype()` back "
                        f"up immediately after the collective (the "
                        f"strategies.py pattern)"))

        # §19: the wire cast happens per bucket, not on the packed
        # vector before bucketing
        cast_vars: Dict[str, int] = {}
        for sub in body_walk(fn):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Call) and \
                    isinstance(sub.value.func, ast.Attribute) and \
                    sub.value.func.attr == "astype" and \
                    sub.value.args and \
                    static_dtype(sub.value.args[0], resolver) in \
                    LOW_PRECISION_DTYPES:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        cast_vars[t.id] = sub.lineno

        def iter_hits(iter_expr, body_nodes):
            if not (isinstance(iter_expr, ast.Name) and
                    iter_expr.id in cast_vars):
                return
            for bn in body_nodes:
                for n in ast.walk(bn):
                    if isinstance(n, ast.Call) and collective_name(
                            resolver.resolve(n.func)) is not None:
                        findings.append(Finding(
                            self.name, sf.path, n.lineno, n.col_offset,
                            f"collective over buckets of "
                            f"`{iter_expr.id}`, which was wire-cast "
                            f"BEFORE bucketing — §19 requires the "
                            f"bf16 cast per bucket so monolithic and "
                            f"bucketed paths stay bit-identical"))
                        return

        for sub in body_walk(fn):
            if isinstance(sub, (ast.For, ast.AsyncFor)):
                iter_hits(sub.iter, sub.body)
            elif isinstance(sub, (ast.ListComp, ast.SetComp,
                                  ast.GeneratorExp)):
                for gen in sub.generators:
                    iter_hits(gen.iter, [sub.elt])
        return findings
