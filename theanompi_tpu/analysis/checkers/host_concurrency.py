"""Host-concurrency pass: shared-state races, lock-order cycles, signal
safety, daemon discipline (docs/design.md §16).

Every concurrency bug in this repo so far was caught by hand review: the
signal-mid-event registry deadlock (PR 4, fixed by making the registry
lock reentrant), the unlocked ``stats_snapshot`` iteration race and the
``threading.Thread._stop`` attribute collision (PRs 8–9).  The runtime
keeps growing threads — PrefetchLoader window producers, the watchdog
monitor, membership heartbeats, center-server handler threads,
ChaosMonkey/ChaosProxy daemons — so this module machine-checks the
class of bug, on top of the engine's thread-role inference
(:meth:`~..engine.ProgramIndex.role_map`):

* **shared-state-race** — an instance attribute (or module global)
  written from ≥2 thread roles, or a container mutated in one role
  while another iterates/copies it (the ``stats_snapshot`` shape), with
  no COMMON lock dominating the conflicting accesses.  Lock dominance
  is interprocedural: an access is guarded by the ``with <lock>:``
  blocks lexically around it PLUS the locks provably held at every
  resolvable call site of its function (the ``request`` →
  ``_request_locked`` → ``_note_fail`` shape).  Whitelists: attributes
  constructed as synchronization/atomic objects (``queue.Queue``,
  ``threading.Event``/locks/threads, ``collections.deque``, executors)
  and writes inside ``__init__``/``__new__`` (construction
  happens-before ``start()``).
* **lock-ordering** — the global lock acquisition graph (nested
  ``with`` blocks, plus calls made while holding a lock into functions
  that transitively acquire).  A cycle between distinct locks is a
  potential deadlock; re-acquiring a known non-reentrant
  ``threading.Lock`` while it is already held is a self-deadlock.
* **signal-safety** — functions reachable from ``signal.signal``
  handlers may not acquire non-reentrant locks (the PR-4
  generalization), block (sleeps, socket connects, queue/thread/event
  waits), spawn threads, or record telemetry (a registry call does
  buffered-file I/O; a signal landing mid-``write`` on the same thread
  raises ``RuntimeError: reentrant call`` inside the BufferedWriter —
  only ``utils/telemetry.py``'s own TERMINAL fatal-signal hook, which
  re-raises with ``SIG_DFL``, is sanctioned).
* **daemon-discipline** — non-daemon threads never joined block
  interpreter exit; a thread object that ESCAPES (stored on ``self``
  or appended to an attribute container) and is started but never
  joined can outlive its owner's ``stop()``; a ``threading.Thread``
  subclass must be daemonic or join itself, and must not shadow Thread
  internals (``self._stop`` — the PR-8 collision).

Scope: findings are reported for runtime code only (``theanompi_tpu/``,
``scripts/``, ``bench.py``).  ``tests/`` spawn threads to *provoke*
races; their spawn sites neither seed roles nor produce findings.
Resolution follows the engine's static-only contract — a duck-typed
call the call graph cannot resolve contributes nothing, so the pass
under-approximates rather than guesses.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from ..core import Checker, Finding, ImportResolver, SourceFile, register
from ..engine import MAIN_ROLE, FuncRecord, ProgramIndex, body_walk

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

# -- vocabulary ---------------------------------------------------------------

#: lock constructors -> reentrancy class
LOCK_CTORS = {
    "threading.Lock": "lock",            # NON-reentrant
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
}

#: attributes constructed as one of these are synchronization / atomic
#: objects — their own methods synchronize, so they are not race state
SYNC_CTORS = set(LOCK_CTORS) | {
    "threading.Event", "threading.Thread", "threading.Timer",
    "threading.local", "threading.Barrier",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue",
    "collections.deque",                 # append/popleft are atomic
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
}

#: container-mutating method names (a call on ``self.X`` counts as a write)
MUTATORS = {"append", "appendleft", "add", "remove", "discard", "pop",
            "popitem", "popleft", "clear", "update", "setdefault",
            "extend", "insert"}

#: reads that traverse the whole container (the iteration-race shape)
COPY_METHODS = {"items", "values", "keys", "copy"}
ITER_WRAPPERS = {"list", "dict", "set", "frozenset", "sorted", "tuple",
                 "sum", "max", "min", "any", "all"}

#: calls a signal handler must not make (module-level, resolver-resolved)
BLOCKING_RESOLVED = {
    "time.sleep", "select.select", "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}
#: blocking methods on ctor-typed receivers: ctor -> method names
BLOCKING_METHODS = {
    "queue.Queue": {"get", "put", "join"},
    "queue.LifoQueue": {"get", "put", "join"},
    "queue.PriorityQueue": {"get", "put", "join"},
    "queue.SimpleQueue": {"get", "put"},
    "threading.Thread": {"join"},
    "threading.Timer": {"join"},
    "threading.Event": {"wait"},
    "threading.Condition": {"wait", "wait_for", "acquire"},
    "threading.Lock": {"acquire"},
    "threading.Semaphore": {"acquire"},
    "subprocess.Popen": {"wait", "communicate"},
}
THREAD_CTORS = {"threading.Thread", "threading.Timer"}

#: telemetry recording surface (mirrors telemetry_hot_path.RECORDING +
#: the accessor-adjacent calls that do registry/file I/O)
TM_RECORDING = {"counter", "gauge", "observe", "phase", "event",
                "system_snapshot", "dump_flight", "summary", "close"}
TELEMETRY_MODULE = "theanompi_tpu.utils.telemetry"
TM_HANDLE_SOURCES = {TELEMETRY_MODULE + ".active", TELEMETRY_MODULE + ".init"}
#: the one module whose handler may record: its own fatal-signal hook is
#: terminal (dump + re-raise with SIG_DFL), per docs/design.md §11/§16
TM_SANCTIONED_PATH = "theanompi_tpu/utils/telemetry.py"

#: ``threading.Thread`` internals a subclass must not shadow (the PR-8
#: ``_stop`` collision: Thread.join() calls self._stop() internally)
THREAD_INTERNALS = {"_started", "_stop", "_target", "_args", "_kwargs",
                    "_name", "_daemonic", "_ident", "_native_id",
                    "_tstate_lock", "_invoke_excepthook", "_stderr",
                    "_initialized"}

_WRITE_KINDS = ("write", "augwrite", "mutwrite")


def _runtime_path(path: str) -> bool:
    return not path.startswith("tests/")


# -- access / scan records ----------------------------------------------------

class Access:
    __slots__ = ("key", "kind", "node", "rec", "held")

    def __init__(self, key, kind, node, rec, held):
        self.key = key                # (owner_id, attr) — owner_id is
        #                               'module.Class' or 'module' (global)
        self.kind = kind              # write|augwrite|mutwrite|iterread
        self.node = node
        self.rec = rec
        self.held = held              # frozenset of syntactically-held locks


class FuncScan:
    __slots__ = ("accesses", "acquires", "calls", "tm_calls", "blocking",
                 "spawns")

    def __init__(self):
        self.accesses: List[Access] = []
        # (lock_id, reentrancy|None, node, held-before frozenset)
        self.acquires: List[Tuple[str, Optional[str], ast.AST,
                                  FrozenSet[str]]] = []
        # (node, tuple of target node ids, held frozenset)
        self.calls: List[Tuple[ast.AST, Tuple[int, ...],
                               FrozenSet[str]]] = []
        self.tm_calls: List[Tuple[ast.AST, str]] = []   # (node, rendered)
        self.blocking: List[Tuple[ast.AST, str]] = []
        self.spawns: List[ast.AST] = []


# -- the shared analysis context ---------------------------------------------

class ConcurrencyContext:
    """One pass over the runtime records, shared by the four checkers
    (cached on the ProgramIndex)."""

    @classmethod
    def get(cls, index: ProgramIndex) -> "ConcurrencyContext":
        ctx = getattr(index, "_host_concurrency_ctx", None)
        if ctx is None:
            ctx = index._host_concurrency_ctx = cls(index)
        return ctx

    def __init__(self, index: ProgramIndex):
        self.index = index
        self.roles = {r.name: r for r in index.thread_roles()}
        #: roles introduced by at least one non-test spawn site — the
        #: only ones that count toward conflicts (tests provoke races
        #: on purpose)
        self.runtime_roles = {
            name for name, r in self.roles.items()
            if any(_runtime_path(s.path) for s in r.sites)}
        self.recs = [r for r in index.records.values()
                     if _runtime_path(r.sf.path)]
        self._module_ctors: Dict[str, Dict[str, str]] = {}
        self._handles: Dict[str, Set[str]] = {}
        self._owner_keys: Dict[str, Tuple[str, str]] = {}
        self._shares_cache: Dict[Tuple[str, str], bool] = {}
        self.scans: Dict[int, FuncScan] = {}
        for rec in self.recs:
            self.scans[id(rec.node)] = self._scan(rec)
        self._held_entry = self._compute_held_at_entry()
        self._trans_acquires = self._compute_transitive_acquires()

    # -- role helpers -------------------------------------------------------

    def roles_of(self, rec: FuncRecord) -> Set[str]:
        roles = {r for r in self.index.roles_of(rec)
                 if r == MAIN_ROLE or r in self.runtime_roles}
        return roles or {MAIN_ROLE}

    def multi_instance(self, role_name: str) -> bool:
        """Roles that run MANY threads at once (one socketserver handler
        per connection, one executor worker per pool slot) — two
        executions of the SAME role race with each other."""
        role = self.roles.get(role_name)
        return role is not None and role.kind in ("handler", "executor")

    def role_shares_owner(self, role_name: str, owner_id: str) -> bool:
        """Does this role provably share INSTANCES of the attribute's
        owner class with other roles?  True when the role's entry is a
        method of that class, a spawn site sits inside one of its
        methods (``Thread(target=self._producer)`` hands ``self`` to
        the new thread), or the role is multi-instance (handlers /
        executor workers share their closures).  ``main`` never shares
        by itself — a conflict needs a concurrent role anchored to the
        class, which is what keeps per-island private models (each
        thread constructs its OWN ModelBase) out of the findings."""
        if role_name == MAIN_ROLE:
            return False
        if self.multi_instance(role_name):
            return True
        role = self.roles.get(role_name)
        if role is None:
            return False
        owner_key = self._owner_keys.get(owner_id)
        if owner_key is None:
            return True                 # module global: trivially shared
        cache = self._shares_cache
        hit = cache.get((role_name, owner_id))
        if hit is not None:
            return hit
        out = any(e.class_key == owner_key for e in role.entries)
        if not out:
            for site in role.sites:
                idx = self.index.file_index[site.sf.path]
                f = idx.enclosing.get(id(site.node))
                while f is not None and not out:
                    cls = idx.class_of.get(id(f))
                    if cls is not None:
                        out = (site.sf.resolver.module,
                               cls.name) == owner_key
                        break
                    f = idx.parent_func.get(id(f))
        cache[(role_name, owner_id)] = out
        return out

    def conflicting_pair(self, owner_id: str, a: "Access", b: "Access"
                         ) -> Optional[Tuple[str, str]]:
        """The first (role_a, role_b) witness that accesses ``a`` and
        ``b`` can touch the SAME object from two live threads, or
        None."""
        for r1 in sorted(self.roles_of(a.rec)):
            for r2 in sorted(self.roles_of(b.rec)):
                if r1 == r2 and not self.multi_instance(r1):
                    continue
                if self.role_shares_owner(r1, owner_id) or \
                        self.role_shares_owner(r2, owner_id):
                    return (r1, r2)
        return None

    # -- identity helpers ---------------------------------------------------

    def module_ctors(self, sf: SourceFile) -> Dict[str, str]:
        """Module-level ``NAME = <ctor>()`` assignments of one file."""
        cached = self._module_ctors.get(sf.path)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        for st in sf.tree.body:
            if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
                resolved = sf.resolver.resolve(st.value.func)
                if resolved:
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            out.setdefault(t.id, resolved)
        self._module_ctors[sf.path] = out
        return out

    def _class_id(self, key: Tuple[str, str]) -> str:
        return f"{key[0]}.{key[1]}"

    def attr_ctor(self, class_key, attr) -> Optional[str]:
        return self.index.class_attr_ctors(class_key).get(attr)

    def _attr_key(self, rec: FuncRecord, expr: ast.AST
                  ) -> Optional[Tuple[Tuple[str, str], Optional[str]]]:
        """``(key, ctor)`` for a shared-state expression:
        ``self.X`` → the enclosing class's attr; ``self.A.B`` → ``B`` on
        ``A``'s constructor class (when known); a bare Name that some
        function in the module writes through ``global`` → module
        global.  None for everything else (locals, parameters)."""
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self":
                if rec.class_key is None:
                    return None
                owner = self._class_id(rec.class_key)
                self._owner_keys.setdefault(owner, rec.class_key)
                return (owner, expr.attr), \
                    self.attr_ctor(rec.class_key, expr.attr)
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and rec.class_key is not None:
                ctor = self.attr_ctor(rec.class_key, base.attr)
                ckey = self.index._class_keys.get(ctor or "")
                if ckey is not None:
                    owner = self._class_id(ckey)
                    self._owner_keys.setdefault(owner, ckey)
                    return (owner, expr.attr), self.attr_ctor(ckey,
                                                              expr.attr)
            return None
        if isinstance(expr, ast.Name):
            module = rec.sf.resolver.module
            if expr.id in self._global_writes(rec.sf):
                return (module, expr.id), \
                    self.module_ctors(rec.sf).get(expr.id)
        return None

    def _global_writes(self, sf: SourceFile) -> Set[str]:
        """Names some function in the module declares ``global`` —
        the module-global shared-state candidates."""
        cached = getattr(sf, "_tpulint_global_names", None)
        if cached is None:
            cached = set()
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Global):
                    cached.update(node.names)
            sf._tpulint_global_names = cached
        return cached

    def lock_id(self, rec: FuncRecord, expr: ast.AST
                ) -> Optional[Tuple[str, Optional[str]]]:
        """``(canonical id, reentrancy class|None)`` when ``expr`` looks
        like a lock being entered, else None.  Identity is the owning
        class + attribute (so ``self._lock`` in two methods — or
        ``self.center._lock`` and ``ElasticCenter``'s own ``self._lock``
        — unify); unresolvable lock-named expressions fall back to a
        per-file textual id (consistent within the file, documented
        approximation)."""
        dotted = ImportResolver.dotted(expr)
        if dotted is None:
            return None
        keyed = self._attr_key(rec, expr)
        if keyed is not None:
            (owner, attr), ctor = keyed
            kind = LOCK_CTORS.get(ctor or "")
            if kind is not None:
                return f"{owner}.{attr}", kind
            if "lock" in attr.lower():
                return f"{owner}.{attr}", None
            return None
        if isinstance(expr, ast.Name):
            ctor = self.module_ctors(rec.sf).get(expr.id)
            kind = LOCK_CTORS.get(ctor or "")
            if kind is not None or "lock" in expr.id.lower():
                return f"{rec.sf.resolver.module}.{expr.id}", kind
            return None
        terminal = dotted.rsplit(".", 1)[-1]
        if "lock" in terminal.lower():
            return f"{rec.sf.path}:{dotted}", None
        return None

    def telemetry_handles(self, sf: SourceFile) -> Set[str]:
        """Dotted names bound to a telemetry registry in one file
        (the telemetry-hot-path discovery, shared here for the
        signal-safety recording rule)."""
        cached = self._handles.get(sf.path)
        if cached is not None:
            return cached
        handles: Set[str] = {"self.telemetry"}
        changed = True
        while changed:
            changed = False
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Assign):
                    continue

                def is_src(v) -> bool:
                    if isinstance(v, ast.Call):
                        return sf.resolver.resolve(v.func) in \
                            TM_HANDLE_SOURCES
                    if isinstance(v, (ast.Name, ast.Attribute)):
                        return ImportResolver.dotted(v) in handles
                    if isinstance(v, ast.IfExp):
                        # `tm = init(...) if record_dir else active()`
                        return is_src(v.body) or is_src(v.orelse)
                    return False

                if not is_src(node.value):
                    continue
                for t in node.targets:
                    name = ImportResolver.dotted(t)
                    if name and name not in handles:
                        handles.add(name)
                        changed = True
        self._handles[sf.path] = handles
        return handles

    # -- the per-function walk ----------------------------------------------

    def _scan(self, rec: FuncRecord) -> FuncScan:
        scan = FuncScan()
        idx = self.index.file_index[rec.sf.path]
        ctor_types = self.index._local_ctor_types(rec)
        handles = self.telemetry_handles(rec.sf)

        def attr_access(expr, kind, node, held):
            keyed = self._attr_key(rec, expr)
            if keyed is None:
                return
            key, ctor = keyed
            if ctor in SYNC_CTORS:
                return                      # synchronization object
            scan.accesses.append(Access(key, kind, node, rec,
                                        frozenset(held)))

        def classify_call(node, held):
            func = node.func
            resolved = rec.sf.resolver.resolve(func)
            if resolved in BLOCKING_RESOLVED:
                scan.blocking.append((node, f"`{resolved}()`"))
            if resolved in THREAD_CTORS:
                scan.spawns.append(node)
            if isinstance(func, ast.Name) and func.id in ITER_WRAPPERS \
                    and len(node.args) == 1 and not node.keywords:
                attr_access(node.args[0], "iterread", node, held)
            if isinstance(func, ast.Attribute):
                recv = func.value
                if func.attr in MUTATORS:
                    attr_access(recv, "mutwrite", node, held)
                elif func.attr in COPY_METHODS:
                    attr_access(recv, "iterread", node, held)
                # blocking method on a ctor-typed receiver (self attr or
                # module-level name; locals stay out of scope — no guess)
                keyed = self._attr_key(rec, recv)
                if keyed is not None:
                    ctor = keyed[1]
                elif isinstance(recv, ast.Name):
                    ctor = self.module_ctors(rec.sf).get(recv.id)
                else:
                    ctor = None
                if ctor in BLOCKING_METHODS and \
                        func.attr in BLOCKING_METHODS[ctor]:
                    base = ImportResolver.dotted(recv) or "<recv>"
                    scan.blocking.append(
                        (node, f"`{base}.{func.attr}()` "
                               f"({ctor.rsplit('.', 1)[-1]})"))
                # telemetry recording
                if func.attr in TM_RECORDING:
                    base = ImportResolver.dotted(recv)
                    rbase = rec.sf.resolver.resolve(recv)
                    if (base in handles) or (rbase == TELEMETRY_MODULE):
                        scan.tm_calls.append(
                            (node, f"{base}.{func.attr}(...)"))
            # call-graph edge — generic names must not fall through to
            # the unique-family fallback here either: a `t.join()` on a
            # Thread resolving to an unrelated in-scope `join` would
            # inject bogus lock-free call sites into the held-at-entry
            # intersection and bogus acquires into transitive_acquires
            enc = idx.enclosing.get(id(func), rec.node)
            targets = self.index.resolve_call(rec.sf, func, enc, ctor_types,
                                              skip_generic_unique=True)
            if targets:
                scan.calls.append(
                    (node, tuple(id(t.node) for t in targets),
                     frozenset(held)))

        def walk(node, held):
            if isinstance(node, _FuncDef):
                return                      # separate record, fresh locks
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in node.items:
                    walk(item.context_expr, held)
                    lid = self.lock_id(rec, item.context_expr)
                    if lid is not None:
                        scan.acquires.append((lid[0], lid[1],
                                              item.context_expr,
                                              frozenset(held)))
                        inner.add(lid[0])
                for st in node.body:
                    walk(st, inner)
                return
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if isinstance(node, ast.AnnAssign) and node.value is None:
                    targets = []        # bare annotation — not a write
                kind = "augwrite" if isinstance(node, ast.AugAssign) \
                    else "write"
                for t in targets:
                    for el in (t.elts if isinstance(t, (ast.Tuple,
                                                        ast.List))
                               else [t]):
                        if isinstance(el, ast.Subscript):
                            attr_access(el.value, "mutwrite", node, held)
                        else:
                            attr_access(el, kind, node, held)
            elif isinstance(node, ast.Call):
                classify_call(node, held)
            elif isinstance(node, ast.For):
                attr_access(node.iter, "iterread", node, held)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    attr_access(gen.iter, "iterread", node, held)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr_access(t.value, "mutwrite", node, held)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for child in ast.iter_child_nodes(rec.node):
            walk(child, set())
        return scan

    # -- interprocedural lock context ---------------------------------------

    def _compute_held_at_entry(self) -> Dict[int, FrozenSet[str]]:
        """locks held at EVERY resolvable call site of each function
        (decreasing fixpoint; thread entries and functions with no call
        sites run lock-free)."""
        sites: Dict[int, List[Tuple[int, FrozenSet[str]]]] = {}
        in_scope = {id(r.node) for r in self.recs}
        for rec in self.recs:
            scan = self.scans[id(rec.node)]
            for node, targets, held in scan.calls:
                for t in targets:
                    if t in in_scope:
                        sites.setdefault(t, []).append((id(rec.node), held))
        entry_ids = set()
        for role in self.roles.values():
            entry_ids.update(id(e.node) for e in role.entries)
        TOP = None                      # the full-universe sentinel
        held: Dict[int, object] = {}
        for rec in self.recs:
            nid = id(rec.node)
            if nid in entry_ids or nid not in sites:
                held[nid] = frozenset()
            else:
                held[nid] = TOP
        for _ in range(len(self.recs) + 1):
            changed = False
            for nid, calls in sites.items():
                if nid in entry_ids:
                    continue            # entries run lock-free, period
                # H[n] = ⋂ over call sites (site_held ∪ H[caller]);
                # TOP is ⋂'s identity.  H[caller] only ever shrinks, so
                # full recomputation converges decreasingly.
                acc = TOP
                for caller, site_held in calls:
                    ch = held.get(caller, frozenset())
                    if ch is TOP:
                        continue        # TOP contributes the identity
                    eff = site_held | ch
                    acc = eff if acc is TOP else (acc & eff)
                if acc is not TOP and acc != held.get(nid):
                    held[nid] = acc
                    changed = True
            if not changed:
                break
        return {nid: (v if v is not TOP else frozenset())
                for nid, v in held.items()}

    def held_at_entry(self, rec: FuncRecord) -> FrozenSet[str]:
        return self._held_entry.get(id(rec.node), frozenset())

    def effective_locks(self, access: Access) -> FrozenSet[str]:
        return access.held | self.held_at_entry(access.rec)

    def _compute_transitive_acquires(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for rec in self.recs:
            direct = {lid for lid, _, _, _ in self.scans[id(rec.node)]
                      .acquires}
            if direct:
                out[id(rec.node)] = set(direct)
        changed = True
        while changed:
            changed = False
            for rec in self.recs:
                scan = self.scans[id(rec.node)]
                cur = out.setdefault(id(rec.node), set())
                for _, targets, _ in scan.calls:
                    for t in targets:
                        extra = out.get(t)
                        if extra and not extra <= cur:
                            cur |= extra
                            changed = True
        return out

    def transitive_acquires(self, rec: FuncRecord) -> Set[str]:
        return self._trans_acquires.get(id(rec.node), set())

    def lock_kind(self, lock_id: str) -> Optional[str]:
        """Reentrancy class of a canonical lock id, when its constructor
        is known."""
        cached = getattr(self, "_lock_kinds", None)
        if cached is None:
            cached = self._lock_kinds = {}
            for rec in self.recs:
                for lid, kind, _, _ in self.scans[id(rec.node)].acquires:
                    if kind is not None:
                        cached.setdefault(lid, kind)
        return cached.get(lock_id)


def _fmt_roles(roles: Sequence[str]) -> str:
    return ", ".join(sorted(roles))


# ---------------------------------------------------------------------------
# shared-state-race
# ---------------------------------------------------------------------------

@register
class SharedStateRaceChecker(Checker):
    name = "shared-state-race"
    description = ("instance attributes / module globals written from "
                   "multiple thread roles (or mutated under another "
                   "role's iteration) without a common lock")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        ctx = ConcurrencyContext.get(index)
        by_key: Dict[Tuple[str, str], List[Access]] = {}
        for rec in ctx.recs:
            fname = rec.name
            for a in ctx.scans[id(rec.node)].accesses:
                if fname in ("__init__", "__new__") and \
                        a.kind in _WRITE_KINDS:
                    continue            # construction happens-before start
                by_key.setdefault(a.key, []).append(a)
        findings: List[Finding] = []
        for key in sorted(by_key, key=lambda k: (k[0], k[1])):
            accesses = sorted(by_key[key],
                              key=lambda a: (a.rec.sf.path,
                                             a.node.lineno,
                                             a.node.col_offset))
            findings.extend(self._check_attr(ctx, key, accesses))
        findings.sort(key=Finding.sort_key)
        return findings

    def _check_attr(self, ctx: ConcurrencyContext, key, accesses
                    ) -> List[Finding]:
        owner, attr = key
        out: List[Finding] = []
        writes = [a for a in accesses if a.kind in _WRITE_KINDS]
        # (a) a PAIR of writes that can land from two live threads on the
        # same object (distinct roles or one multi-instance role, with
        # instance-sharing evidence) and holds no common lock
        for i, w1 in enumerate(writes):
            for w2 in writes[i:]:
                pair = ctx.conflicting_pair(owner, w1, w2)
                if pair is None:
                    continue
                if ctx.effective_locks(w1) & ctx.effective_locks(w2):
                    continue
                anchor = w1 if not ctx.effective_locks(w1) else w2
                out.append(Finding(
                    self.name, anchor.rec.sf.path, anchor.node.lineno,
                    anchor.node.col_offset,
                    f"`{attr}` on `{owner}` is written from thread "
                    f"roles {_fmt_roles(set(pair))} that can run "
                    f"concurrently on one instance, with no common "
                    f"lock — guard every write with the same "
                    f"`with <lock>:` or confine writes to one role"))
                return out              # one finding per attribute
        # (b) container mutated in one role while another iterates/copies
        mut_writes = [a for a in writes if a.kind == "mutwrite"]
        iter_reads = [a for a in accesses if a.kind == "iterread"]
        for r in iter_reads:
            r_locks = ctx.effective_locks(r)
            for w in mut_writes:
                pair = ctx.conflicting_pair(owner, w, r)
                if pair is None:
                    continue
                if r_locks & ctx.effective_locks(w):
                    continue
                out.append(Finding(
                    self.name, r.rec.sf.path, r.node.lineno,
                    r.node.col_offset,
                    f"unlocked iteration/copy of `{attr}` on `{owner}` "
                    f"while role(s) {_fmt_roles(ctx.roles_of(w.rec))} "
                    f"mutate it (write at {w.rec.sf.path}:"
                    f"{w.node.lineno}) — the stats_snapshot race class; "
                    f"take the same lock around both sides"))
                break                   # one finding per read site
        return out


# ---------------------------------------------------------------------------
# lock-ordering
# ---------------------------------------------------------------------------

@register
class LockOrderingChecker(Checker):
    name = "lock-ordering"
    description = ("cycles in the lock acquisition graph (nested `with` "
                   "blocks + calls made while holding a lock) and "
                   "non-reentrant self-acquisition")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        ctx = ConcurrencyContext.get(index)
        # edges[a][b] = (sf, node) witness for a held -> b acquired
        edges: Dict[str, Dict[str, Tuple]] = {}
        findings: List[Finding] = []
        for rec in ctx.recs:
            scan = ctx.scans[id(rec.node)]
            entry_held = ctx.held_at_entry(rec)
            for lid, kind, node, held in scan.acquires:
                for a in sorted(held | entry_held):
                    if a == lid:
                        if ctx.lock_kind(lid) == "lock":
                            findings.append(Finding(
                                self.name, rec.sf.path, node.lineno,
                                node.col_offset,
                                f"non-reentrant lock `{lid}` re-acquired "
                                f"while already held — self-deadlock "
                                f"(use RLock or release first)"))
                        continue
                    edges.setdefault(a, {}).setdefault(
                        lid, (rec.sf, node))
            for node, targets, held in scan.calls:
                if not (held or entry_held):
                    continue
                acquired: Set[str] = set()
                for t in targets:
                    trec = index.records.get(t)
                    if trec is not None:
                        acquired |= ctx.transitive_acquires(trec)
                for a in sorted(held | entry_held):
                    for b in sorted(acquired):
                        if a == b:
                            if ctx.lock_kind(a) == "lock":
                                findings.append(Finding(
                                    self.name, rec.sf.path, node.lineno,
                                    node.col_offset,
                                    f"call while holding non-reentrant "
                                    f"lock `{a}` reaches a function that "
                                    f"acquires it again — self-deadlock"))
                            continue
                        edges.setdefault(a, {}).setdefault(
                            b, (rec.sf, node))
        findings.extend(self._cycles(edges))
        findings.sort(key=Finding.sort_key)
        return findings

    def _cycles(self, edges) -> List[Finding]:
        out: List[Finding] = []
        reported: Set[FrozenSet[str]] = set()
        for start in sorted(edges):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(edges.get(node, ())):
                    if nxt == start:
                        cyc = frozenset(path)
                        if cyc in reported or len(path) < 2:
                            continue
                        reported.add(cyc)
                        sf, wnode = edges[path[-1]][start]
                        chain = " -> ".join(path + [start])
                        out.append(Finding(
                            self.name, sf.path, wnode.lineno,
                            wnode.col_offset,
                            f"lock-order cycle: {chain} — two threads "
                            f"taking these locks in different orders can "
                            f"deadlock; impose one global order"))
                    elif nxt not in path and len(path) < 8:
                        stack.append((nxt, path + [nxt]))
        return out


# ---------------------------------------------------------------------------
# signal-safety
# ---------------------------------------------------------------------------

@register
class SignalSafetyChecker(Checker):
    name = "signal-safety"
    description = ("functions reachable from signal handlers must not "
                   "acquire non-reentrant locks, block, spawn threads, "
                   "or record telemetry (reentrant-BufferedWriter "
                   "hazard; the terminal fatal-signal hook in "
                   "utils/telemetry.py is the one sanctioned recorder)")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        ctx = ConcurrencyContext.get(index)
        findings: List[Finding] = []
        seen_members: Set[int] = set()
        for role in index.thread_roles():
            if role.kind != "signal" or role.name not in ctx.runtime_roles:
                continue
            for rec in index.role_members(role):
                if id(rec.node) in seen_members:
                    continue
                seen_members.add(id(rec.node))
                if not _runtime_path(rec.sf.path):
                    continue
                findings.extend(self._check_member(ctx, index, rec))
        findings.sort(key=Finding.sort_key)
        return findings

    def _check_member(self, ctx, index, rec: FuncRecord) -> List[Finding]:
        out: List[Finding] = []
        scan = ctx.scans.get(id(rec.node))
        if scan is None:
            return out
        where = f"signal-handler-reachable `{rec.qualname}`"
        for lid, kind, node, _held in scan.acquires:
            if kind == "lock":
                out.append(Finding(
                    self.name, rec.sf.path, node.lineno, node.col_offset,
                    f"{where} acquires NON-reentrant lock `{lid}` — a "
                    f"signal landing while the interrupted thread holds "
                    f"it deadlocks the process (the PR-4 class; use "
                    f"RLock or keep handlers lock-free)"))
        for node, targets, _held in scan.calls:
            reached = set()
            for t in targets:
                trec = index.records.get(t)
                if trec is not None:
                    reached |= {lid for lid in ctx.transitive_acquires(trec)
                                if ctx.lock_kind(lid) == "lock"}
            for lid in sorted(reached):
                out.append(Finding(
                    self.name, rec.sf.path, node.lineno, node.col_offset,
                    f"{where} calls into code acquiring NON-reentrant "
                    f"lock `{lid}` — deadlock if the signal interrupts "
                    f"a holder"))
        for node, desc in scan.blocking:
            out.append(Finding(
                self.name, rec.sf.path, node.lineno, node.col_offset,
                f"{where} blocks on {desc} — a signal handler must "
                f"return promptly (it runs on the main thread mid-"
                f"bytecode); set a flag/Event and handle it in the loop"))
        for node in scan.spawns:
            out.append(Finding(
                self.name, rec.sf.path, node.lineno, node.col_offset,
                f"{where} spawns a thread — thread bootstrap takes "
                f"interpreter-internal locks the interrupted thread may "
                f"hold; defer the spawn to the main loop"))
        if rec.sf.path != TM_SANCTIONED_PATH:
            for node, rendered in scan.tm_calls:
                out.append(Finding(
                    self.name, rec.sf.path, node.lineno, node.col_offset,
                    f"{where} records telemetry (`{rendered}`) — the "
                    f"registry does buffered-file I/O, and a signal "
                    f"landing mid-write on the same thread raises "
                    f"`RuntimeError: reentrant call` inside the "
                    f"BufferedWriter; only the terminal fatal-signal "
                    f"hook in utils/telemetry.py (dump + re-raise with "
                    f"SIG_DFL) is sanctioned (docs/design.md §16)"))
        return out


# ---------------------------------------------------------------------------
# daemon-discipline
# ---------------------------------------------------------------------------

@register
class DaemonDisciplineChecker(Checker):
    name = "daemon-discipline"
    description = ("non-daemon threads never joined; escaping started "
                   "threads without a bounded join; Thread subclasses "
                   "shadowing threading internals")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        ctx = ConcurrencyContext.get(index)
        findings: List[Finding] = []
        for site in index.spawn_sites():
            if not _runtime_path(site.path):
                continue
            if site.kind in ("thread", "timer"):
                findings.extend(self._check_ctor_site(index, site))
            elif site.kind == "thread-subclass":
                findings.extend(self._check_subclass(index, site))
        findings.sort(key=Finding.sort_key)
        return findings

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _kw_true(call: ast.Call, name: str) -> bool:
        for kw in call.keywords:
            if kw.arg == name and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False

    @staticmethod
    def _join_targets(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
        """(dotted receivers of ``.join(`` calls, container attrs whose
        loop variable is joined) within ``tree``."""
        joined: Set[str] = set()
        containers: Set[str] = set()
        loop_vars: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name):
                it = ImportResolver.dotted(node.iter)
                if it:
                    loop_vars[node.target.id] = it
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join":
                recv = ImportResolver.dotted(node.func.value)
                if recv:
                    joined.add(recv)
                    if recv in loop_vars:
                        containers.add(loop_vars[recv])
        return joined, containers

    def _scope_tree(self, index, site) -> ast.AST:
        """The join-discipline search scope: the enclosing class body if
        the spawn happens in a method, else the whole module."""
        idx = index.file_index[site.sf.path]
        enc = idx.enclosing.get(id(site.node))
        f = enc
        while f is not None:
            cls = idx.class_of.get(id(f))
            if cls is not None:
                return cls
            f = idx.parent_func.get(id(f))
        return site.sf.tree

    def _check_ctor_site(self, index, site) -> List[Finding]:
        call = site.node
        idx = index.file_index[site.sf.path]
        enc = idx.enclosing.get(id(call))
        parent_src = enc if enc is not None else site.sf.tree
        # binding: the statement the constructor appears in
        stored_attr = local_name = None
        for sub in ast.walk(parent_src):
            if isinstance(sub, ast.Assign) and sub.value is call:
                t = sub.targets[0]
                if isinstance(t, ast.Attribute):
                    stored_attr = ImportResolver.dotted(t)
                elif isinstance(t, ast.Name):
                    local_name = t.id
                break
        daemon = self._kw_true(call, "daemon")
        started = False
        appended_to = None
        binding = local_name or stored_attr
        if not daemon and binding and enc is not None:
            # post-construction daemonization: `t.daemon = True` AND the
            # stored-attr shape `self._t.daemon = True`
            for sub in body_walk(enc):
                if isinstance(sub, ast.Assign) and \
                        ImportResolver.dotted(sub.targets[0] if
                                              sub.targets else None) == \
                        f"{binding}.daemon" and \
                        isinstance(sub.value, ast.Constant) and \
                        sub.value.value:
                    daemon = True
        if enc is not None and (local_name or stored_attr):
            for sub in body_walk(enc):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute):
                    recv = ImportResolver.dotted(sub.func.value)
                    if recv in (local_name, stored_attr) and \
                            sub.func.attr == "start":
                        started = True
                    if sub.func.attr == "append" and sub.args and \
                            local_name is not None and \
                            isinstance(sub.args[0], ast.Name) and \
                            sub.args[0].id == local_name:
                        appended_to = ImportResolver.dotted(sub.func.value)
        # chained Thread(...).start()
        chained = False
        for sub in ast.walk(parent_src):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "start" and sub.func.value is call:
                chained = started = True
        scope = self._scope_tree(index, site)
        joined, join_containers = self._join_targets(scope)
        out: List[Finding] = []
        kind = "Timer" if site.kind == "timer" else "Thread"
        if stored_attr is not None:
            escapes_as = stored_attr
            is_joined = stored_attr in joined
        elif appended_to is not None:
            escapes_as = appended_to
            is_joined = appended_to in join_containers
        else:
            escapes_as = None
            is_joined = (local_name in joined) if local_name else False
        if not daemon and not is_joined:
            out.append(Finding(
                self.name, site.path, site.line, call.col_offset,
                f"non-daemon {kind} (target `{site.target_desc}`) with "
                f"no join() in scope — it blocks interpreter exit and "
                f"outlives its owner; pass daemon=True or join it on "
                f"every shutdown path"))
        elif escapes_as is not None and started and not is_joined:
            out.append(Finding(
                self.name, site.path, site.line, call.col_offset,
                f"{kind} stored on `{escapes_as}` is start()ed but "
                f"never joined — it can outlive stop(); add a bounded "
                f"join (join(timeout=...)) on the shutdown path"))
        if chained and not daemon:
            pass                        # already covered by the first arm
        return out

    def _check_subclass(self, index, site) -> List[Finding]:
        cls = site.node                 # the ClassDef
        out: List[Finding] = []
        # internals shadowing: any method assigning self.<internal>
        for sub in ast.walk(cls):
            if not isinstance(sub, ast.Assign):
                continue
            for t in sub.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self" and \
                        t.attr in THREAD_INTERNALS:
                    out.append(Finding(
                        self.name, site.path, sub.lineno, sub.col_offset,
                        f"Thread subclass `{cls.name}` assigns "
                        f"`self.{t.attr}`, shadowing a threading.Thread "
                        f"internal — the PR-8 `_stop` collision class; "
                        f"rename the attribute"))
        # daemon / join discipline of the subclass itself
        daemonic = False
        for sub in ast.walk(cls):
            if isinstance(sub, ast.Call) and self._kw_true(sub, "daemon"):
                daemonic = True
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) and \
                            ImportResolver.dotted(t) == "self.daemon" and \
                            isinstance(sub.value, ast.Constant) and \
                            sub.value.value:
                        daemonic = True
        joined, _ = self._join_targets(cls)
        self_joins = any(j == "self" or j.startswith("self.")
                         for j in joined) or \
            any(isinstance(n, ast.Call) and
                isinstance(n.func, ast.Attribute) and
                n.func.attr == "join" and
                isinstance(n.func.value, ast.Name) and
                n.func.value.id == "self"
                for n in ast.walk(cls))
        if not daemonic and not self_joins:
            out.append(Finding(
                self.name, site.path, site.line, cls.col_offset,
                f"Thread subclass `{cls.name}` is non-daemon and never "
                f"joins itself — instances outlive their owners and "
                f"block interpreter exit; pass daemon=True to "
                f"super().__init__ or join in a stop() method"))
        return out

