"""schema-drift: recorder/telemetry phase vocabulary stays in sync.

Absorbs ``scripts/check_schema_drift.py`` (now a deprecation shim that
execs the lint CLI): three consumers must agree on the phase/section
vocabulary with ``telemetry.PHASES`` as the ONE source of truth —
``recorder.SECTIONS``, the ``print_train_info`` record keys
(``t_<section>``), and the telemetry phase-event names.  A bucket added
to one but not the others silently drops that phase from records,
plots, or reports.

Unlike the AST checkers this is a PROJECT-level probe against LIVE
objects (a Recorder driven through one print, a Telemetry instance fed
one bracket per phase), so a hand-rolled record dict drifting from the
declared list is caught too.  Both modules import without jax
(``telemetry`` is stdlib-only by contract, ``recorder`` needs numpy),
so the lint CLI stays backend-free.
"""

from __future__ import annotations

from typing import List

from ..core import Checker, Finding, register

TELEMETRY_PATH = "theanompi_tpu/utils/telemetry.py"
RECORDER_PATH = "theanompi_tpu/utils/recorder.py"


def live_drift_errors(recorder, telemetry) -> List[tuple]:
    """The live-object checks, parameterized on the two modules so tests
    can probe failure modes with monkeypatched stand-ins.  Returns
    ``(path, message)`` pairs; empty = in sync."""
    errors: List[tuple] = []

    # 1. recorder.SECTIONS must BE the canonical list
    if tuple(recorder.SECTIONS) != tuple(telemetry.PHASES):
        errors.append((RECORDER_PATH,
                       f"recorder.SECTIONS {tuple(recorder.SECTIONS)!r} != "
                       f"telemetry.PHASES {tuple(telemetry.PHASES)!r}"))

    # 2. the record keys a live print_train_info actually emits
    r = recorder.Recorder({"verbose": False, "printFreq": 1})
    r.start()
    r.end("train")
    r.train_error(1, 1.0, 0.5, 8)
    rec = r.print_train_info(1)
    if not rec:
        errors.append((RECORDER_PATH,
                       "print_train_info(1) did not fire at printFreq=1"))
    else:
        got = {k for k in rec if k.startswith("t_")}
        want = {"t_" + s for s in telemetry.PHASES if s != "val"}
        if got != want:
            errors.append((RECORDER_PATH,
                           f"print_train_info record keys {sorted(got)} != "
                           f"t_<PHASES except val> {sorted(want)}"))
    if tuple(recorder.RECORD_KEYS) != tuple(
            "t_" + s for s in telemetry.PHASES if s != "val"):
        errors.append((RECORDER_PATH,
                       f"recorder.RECORD_KEYS {tuple(recorder.RECORD_KEYS)!r}"
                       " drifted from telemetry.PHASES"))

    # 3. the phase-event names a live registry emits for each section
    tm = telemetry.Telemetry(rank=0, run_id="drift-check")
    for s in telemetry.PHASES:
        tm.phase(s, 0.0)
    evs = [e for e in tm.tail(len(telemetry.PHASES) + 1)
           if e["ev"] == "phase"]
    got_secs = {e.get("sec") for e in evs}
    if got_secs != set(telemetry.PHASES):
        errors.append((TELEMETRY_PATH,
                       f"telemetry phase-event names {sorted(got_secs)} != "
                       f"PHASES {sorted(telemetry.PHASES)}"))
    got_hists = {k for k in tm.hists if k.startswith("phase.")}
    if got_hists != {"phase." + s for s in telemetry.PHASES}:
        errors.append((TELEMETRY_PATH,
                       f"telemetry phase histograms {sorted(got_hists)} "
                       "drifted from PHASES"))
    return errors


@register
class SchemaDriftChecker(Checker):
    name = "schema-drift"
    description = ("recorder.SECTIONS / print_train_info record keys / "
                   "telemetry phase events must derive from telemetry."
                   "PHASES (live-object probe)")
    reads_files = False    # `--only schema-drift` skips the repo parse

    def check_project(self, files):
        # normal import both under pytest (real package loaded) and under
        # the lint CLI (scripts/lint.py registers a synthetic
        # `theanompi_tpu` parent whose __path__ skips the jax-importing
        # package __init__)
        from theanompi_tpu.utils import recorder, telemetry
        return [Finding(self.name, path, 1, 0, msg)
                for path, msg in live_drift_errors(recorder, telemetry)]
