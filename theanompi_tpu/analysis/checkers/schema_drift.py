"""schema-drift: recorder/telemetry phase vocabulary stays in sync.

Absorbs ``scripts/check_schema_drift.py`` (now a deprecation shim that
execs the lint CLI): three consumers must agree on the phase/section
vocabulary with ``telemetry.PHASES`` as the ONE source of truth —
``recorder.SECTIONS``, the ``print_train_info`` record keys
(``t_<section>``), and the telemetry phase-event names.  A bucket added
to one but not the others silently drops that phase from records,
plots, or reports.

Round 12 extends the probe to the device-attribution schema
(docs/design.md §13): ``devprof.feed_telemetry`` must emit exactly the
declared ``device.*`` gauge vocabulary (``devprof.DEVICE_GAUGES``), the
training sentry must emit the ``anomaly`` event with a ``kind`` from
``sentry.ANOMALY_KINDS``, the bench trace columns must be exactly
``devprof.TRACE_ROW_COLUMNS`` (what ``profile_row_fields`` emits), and
``scripts/telemetry_report.py``'s consumed-event vocabulary
(``TRACKED_EVENTS``) must cover every emitter — so a new emitter can't
stream events the report and Perfetto export silently drop.

Round 19 adds the protocol cross-check (docs/design.md §21): the
center op table the ``analysis/protocol.py`` extraction reads out of
``center_server.py`` must equal the ops a LIVE ``RemoteCenter``
actually sends against a stubbed wire — the static view the
wire-contract/retry-safety checkers rest on is pinned to the runtime
surface, so an extraction rule going stale fails the gate instead of
silently blinding the protocol pass.

Unlike the AST checkers this is a PROJECT-level probe against LIVE
objects (a Recorder driven through one print, a Telemetry instance fed
one bracket per phase, a sentry pushed into an anomaly), so a
hand-rolled record dict drifting from the declared list is caught too.
All probed modules import without jax (``telemetry``/``devprof``/
``sentry`` are stdlib-only by contract, ``recorder`` needs numpy), so
the lint CLI stays backend-free.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

from ..core import Checker, Finding, register
# endpoint files have ONE home — the §21 protocol model; re-declaring
# them here let the probe parse one path while anchoring findings to
# another if a module ever moved (review finding, round 19)
from ..protocol import (CENTER_PATH, FLEETMON_PATH, MEMBERSHIP_PATH,
                        TRACING_PATH, WIRE_PATH)
# the key_extra vocabulary has ONE home — the compile-surface pass; the
# round-26 probe cross-checks it against a live stamping run
from .compile_surface import COMPILE_CACHE_PATH

TELEMETRY_PATH = "theanompi_tpu/utils/telemetry.py"
RECORDER_PATH = "theanompi_tpu/utils/recorder.py"
DEVPROF_PATH = "theanompi_tpu/utils/devprof.py"
SENTRY_PATH = "theanompi_tpu/utils/sentry.py"
REPORT_PATH = "scripts/telemetry_report.py"
CHAOS_PATH = "theanompi_tpu/utils/chaos.py"
NUMERICS_PATH = "theanompi_tpu/utils/numerics.py"

# one lane, one module: a compute span [0,50]us and a comm span [40,60]us
# → compute 50us, comm 20us, exposed 10us, overlap 0.5 — a COMPLETE
# profile, so feed_telemetry must emit every declared gauge
_PROBE_EVENTS = [
    {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 50.0,
     "name": "fusion.1",
     "args": {"hlo_op": "fusion.1", "hlo_module": "jit_step"}},
    {"ph": "X", "pid": 1, "tid": 1, "ts": 40.0, "dur": 20.0,
     "name": "all-reduce.1",
     "args": {"hlo_op": "all-reduce.1", "hlo_module": "jit_step"}},
]


def live_drift_errors(recorder, telemetry) -> List[tuple]:
    """The live-object checks, parameterized on the two modules so tests
    can probe failure modes with monkeypatched stand-ins.  Returns
    ``(path, message)`` pairs; empty = in sync."""
    errors: List[tuple] = []

    # 1. recorder.SECTIONS must BE the canonical list
    if tuple(recorder.SECTIONS) != tuple(telemetry.PHASES):
        errors.append((RECORDER_PATH,
                       f"recorder.SECTIONS {tuple(recorder.SECTIONS)!r} != "
                       f"telemetry.PHASES {tuple(telemetry.PHASES)!r}"))

    # 2. the record keys a live print_train_info actually emits
    r = recorder.Recorder({"verbose": False, "printFreq": 1})
    r.start()
    r.end("train")
    r.train_error(1, 1.0, 0.5, 8)
    rec = r.print_train_info(1)
    if not rec:
        errors.append((RECORDER_PATH,
                       "print_train_info(1) did not fire at printFreq=1"))
    else:
        got = {k for k in rec if k.startswith("t_")}
        want = {"t_" + s for s in telemetry.PHASES if s != "val"}
        if got != want:
            errors.append((RECORDER_PATH,
                           f"print_train_info record keys {sorted(got)} != "
                           f"t_<PHASES except val> {sorted(want)}"))
    if tuple(recorder.RECORD_KEYS) != tuple(
            "t_" + s for s in telemetry.PHASES if s != "val"):
        errors.append((RECORDER_PATH,
                       f"recorder.RECORD_KEYS {tuple(recorder.RECORD_KEYS)!r}"
                       " drifted from telemetry.PHASES"))

    # 3. the phase-event names a live registry emits for each section
    tm = telemetry.Telemetry(rank=0, run_id="drift-check")
    for s in telemetry.PHASES:
        tm.phase(s, 0.0)
    evs = [e for e in tm.tail(len(telemetry.PHASES) + 1)
           if e["ev"] == "phase"]
    got_secs = {e.get("sec") for e in evs}
    if got_secs != set(telemetry.PHASES):
        errors.append((TELEMETRY_PATH,
                       f"telemetry phase-event names {sorted(got_secs)} != "
                       f"PHASES {sorted(telemetry.PHASES)}"))
    got_hists = {k for k in tm.hists if k.startswith("phase.")}
    if got_hists != {"phase." + s for s in telemetry.PHASES}:
        errors.append((TELEMETRY_PATH,
                       f"telemetry phase histograms {sorted(got_hists)} "
                       "drifted from PHASES"))
    return errors


def device_schema_errors(devprof, sentry, telemetry,
                         telemetry_report=None) -> List[tuple]:
    """The round-12 device-attribution probes, parameterized on the live
    modules.  ``telemetry_report`` may be None (script not in the linted
    tree — e.g. a restricted pre-commit checkout); its cross-checks are
    then skipped."""
    errors: List[tuple] = []

    # 1. feed_telemetry emits EXACTLY the declared device.* gauge set
    prof = devprof.attribute(_PROBE_EVENTS)
    tm = telemetry.Telemetry(rank=0, run_id="drift-check")
    devprof.feed_telemetry(prof, tm)
    if set(tm.gauges) != set(devprof.DEVICE_GAUGES):
        errors.append((DEVPROF_PATH,
                       f"feed_telemetry gauges {sorted(tm.gauges)} != "
                       f"DEVICE_GAUGES {sorted(devprof.DEVICE_GAUGES)}"))
    prof_evs = [e for e in tm.tail(4) if e["ev"] == devprof.PROFILE_EVENT]
    if not prof_evs:
        errors.append((DEVPROF_PATH,
                       f"feed_telemetry emitted no "
                       f"{devprof.PROFILE_EVENT!r} event"))
    if any(not g.startswith("device.") for g in devprof.DEVICE_GAUGES):
        errors.append((DEVPROF_PATH,
                       "DEVICE_GAUGES contains a non-'device.' name"))

    # 2. bench trace columns: profile_row_fields emits exactly the
    # declared column set (bench.py folds its return verbatim)
    fields = devprof.profile_row_fields(prof, total_flops=1e9,
                                        peak_flops=1e12)
    if set(fields) != set(devprof.TRACE_ROW_COLUMNS):
        errors.append((DEVPROF_PATH,
                       f"profile_row_fields keys {sorted(fields)} != "
                       f"TRACE_ROW_COLUMNS "
                       f"{sorted(devprof.TRACE_ROW_COLUMNS)}"))

    # 2b. the bucketed-wire row columns (BENCH_BUCKET_BYTES rows) must
    # stay disjoint from the trace vocabulary — a collision would let one
    # emitter silently overwrite the other's column in the row JSON —
    # and bench.py must emit exactly the declared names (string-level
    # probe: bench imports jax, so the live-row check stays lexical)
    bucket_cols = getattr(devprof, "BUCKET_ROW_COLUMNS", None)
    if not bucket_cols:
        errors.append((DEVPROF_PATH,
                       "BUCKET_ROW_COLUMNS missing from devprof — the "
                       "bucketed bench rows have no pinned vocabulary"))
    else:
        clash = sorted(set(bucket_cols) & set(devprof.TRACE_ROW_COLUMNS))
        if clash:
            errors.append((DEVPROF_PATH,
                           f"BUCKET_ROW_COLUMNS collide with "
                           f"TRACE_ROW_COLUMNS: {clash}"))
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        bench_path = os.path.join(root, "bench.py")
        if os.path.exists(bench_path):
            with open(bench_path) as f:
                src = f.read()
            missing = [c for c in bucket_cols if f'"{c}"' not in src]
            if missing:
                errors.append(("bench.py",
                               f"bucketed row column(s) {missing} "
                               f"declared in devprof.BUCKET_ROW_COLUMNS "
                               "never appear in bench.py — the rows "
                               "would ship without them"))

    # 3. the sentry's anomaly event: a live instance pushed into a NaN
    # must emit ANOMALY_EVENT with a declared kind and an iter field
    tm2 = telemetry.Telemetry(rank=0, run_id="drift-check")
    s = sentry.TrainingSentry({"verbose": False, "sentry_min_records": 2},
                              telemetry=tm2)
    for i in range(3):
        s.observe_record({"iter": i, "cost": 1.0, "images_per_sec": 100.0})
    kind = s.observe_record({"iter": 3, "cost": float("nan"),
                             "images_per_sec": 100.0})
    anoms = [e for e in tm2.tail(8) if e["ev"] == sentry.ANOMALY_EVENT]
    if kind != "nan_loss" or not anoms:
        errors.append((SENTRY_PATH,
                       "a NaN cost did not raise a live "
                       f"{sentry.ANOMALY_EVENT!r} event (got kind "
                       f"{kind!r})"))
    else:
        ev = anoms[-1]
        if ev.get("kind") not in sentry.ANOMALY_KINDS:
            errors.append((SENTRY_PATH,
                           f"anomaly kind {ev.get('kind')!r} not in "
                           f"ANOMALY_KINDS {sentry.ANOMALY_KINDS}"))
        if "iter" not in ev:
            errors.append((SENTRY_PATH,
                           "anomaly event carries no 'iter' field"))

    # 4. the report/Perfetto converter consumes every emitter's vocabulary
    if telemetry_report is not None:
        tracked = set(getattr(telemetry_report, "TRACKED_EVENTS", ()))
        want = {"phase", "train_record", "gauges",
                sentry.ANOMALY_EVENT, devprof.PROFILE_EVENT}
        missing = sorted(want - tracked)
        if missing:
            errors.append((REPORT_PATH,
                           f"TRACKED_EVENTS is missing emitter event "
                           f"kind(s) {missing} — the report/trace export "
                           "would silently drop them"))
    return errors


def membership_schema_errors(membership, chaos, telemetry,
                             telemetry_report=None) -> List[tuple]:
    """Round-13 probes: the elastic-membership event vocabulary.  A LIVE
    controller driven through join → demote → leave must emit exactly the
    declared :data:`MEMBERSHIP_EVENTS` kinds (each tagged with the worker
    id), a live ``WorkerLease.beat`` must stream its declared heartbeat
    gauges, and the report/trace converter must consume all of it —
    otherwise the chaos gate's leave/join matching silently sees nothing.
    ``membership``/``chaos`` are the live modules (file-path loaded in the
    jax-free lint CLI); either may be None in a partial tree."""
    errors: List[tuple] = []
    if membership is not None:
        tm = telemetry.Telemetry(rank=0, run_id="drift-check")
        ctl = membership.MembershipController(telemetry_=tm)
        ctl.join(7, pid=123)
        ctl.demote(7)            # refused: would empty the active set
        ctl.join(8, pid=124)
        ctl.demote(7)
        ctl.leave(8, reason="probe")
        evs = [e for e in tm.tail(8) if e["ev"] != "run_start"]
        got = {e["ev"] for e in evs}
        if got != set(membership.MEMBERSHIP_EVENTS):
            errors.append((MEMBERSHIP_PATH,
                           f"a live controller's join/demote/leave emitted "
                           f"{sorted(got)} != MEMBERSHIP_EVENTS "
                           f"{sorted(membership.MEMBERSHIP_EVENTS)}"))
        if any("worker" not in e for e in evs):
            errors.append((MEMBERSHIP_PATH,
                           "a membership event carries no 'worker' field"))
        # heartbeat gauges: one live beat streams the declared keys
        import tempfile
        tm2 = telemetry.Telemetry(rank=0, run_id="drift-check")
        with tempfile.TemporaryDirectory() as d:
            lease = membership.WorkerLease(d, 0, telemetry_=tm2)
            lease.beat(5)
        beats = [e for e in tm2.tail(4) if e["ev"] == "gauges"]
        want_g = set(membership.HEARTBEAT_GAUGES)
        if not beats or not want_g <= set(beats[-1]):
            errors.append((MEMBERSHIP_PATH,
                           f"WorkerLease.beat streamed no gauges event "
                           f"carrying HEARTBEAT_GAUGES {sorted(want_g)}"))
        if set(tm2.gauges) != want_g:
            errors.append((MEMBERSHIP_PATH,
                           f"WorkerLease.beat gauges {sorted(tm2.gauges)} "
                           f"!= HEARTBEAT_GAUGES {sorted(want_g)}"))
    if telemetry_report is not None:
        tracked = set(getattr(telemetry_report, "TRACKED_EVENTS", ()))
        want = set(getattr(membership, "MEMBERSHIP_EVENTS", ())) if \
            membership is not None else set()
        if chaos is not None:
            want.add(chaos.FAULT_EVENT)
        missing = sorted(want - tracked)
        if missing:
            errors.append((REPORT_PATH,
                           f"TRACKED_EVENTS is missing membership/chaos "
                           f"event kind(s) {missing} — the chaos gate's "
                           "leave/join matching would silently drop them"))
        counters = set(getattr(telemetry_report, "TRACE_COUNTER_KEYS", ()))
        hb = set(getattr(membership, "HEARTBEAT_GAUGES", ())) if \
            membership is not None else set()
        if hb and not hb <= counters:
            errors.append((REPORT_PATH,
                           f"TRACE_COUNTER_KEYS is missing heartbeat "
                           f"gauge(s) {sorted(hb - counters)} — the "
                           "Perfetto export would not render liveness"))
    return errors


def wire_schema_errors(wire, membership, telemetry,
                       telemetry_report=None) -> List[tuple]:
    """Round-14 probes: the resilient-RPC telemetry vocabulary.  A LIVE
    wire client driven into a dead address must tick its declared
    counters and emit the declared ``wire`` give-up event; a live dedup
    window replaying a token must tick ``wire.dedup_hit``; a crafted
    version-mismatch frame must fail loudly with BOTH versions in the
    message; the controller's center-outage pair must emit exactly
    :data:`CENTER_EVENTS`; and the report/trace converter must consume
    all of it.  ``wire``/``membership`` are file-path-loaded live modules
    (jax-free); either may be None in a partial tree."""
    errors: List[tuple] = []
    if wire is None:
        return errors

    # 0. declared names are wire-namespaced (report renders by prefix)
    for name in (wire.WIRE_COUNTERS + wire.WIRE_HISTS + wire.WIRE_GAUGES):
        if not name.startswith("wire."):
            errors.append((WIRE_PATH,
                           f"declared wire metric {name!r} is outside the "
                           f"'wire.' namespace"))

    # 1. a live client against a dead address: retries, then a loud
    # give-up — declared counters tick, the declared event kind streams
    if membership is not None:
        tm = telemetry.Telemetry(rank=0, run_id="drift-check")
        client = wire.WireClient(
            "127.0.0.1:9", client_id="drift", op_timeout_s=0.2,
            connect_timeout_s=0.2, max_retries=1, deadline_s=1.0,
            backoff=membership.Backoff(base=0.01, cap=0.02),
            telemetry_=tm)
        gave_up = False
        try:
            client.request({"op": "stats"})
        except ConnectionError:
            gave_up = True
        if not gave_up:
            errors.append((WIRE_PATH,
                           "a WireClient against a dead address did not "
                           "raise WireGiveUp"))
        if tm.counters.get("wire.giveup", 0) < 1 or \
                tm.counters.get("wire.retry", 0) < 1:
            errors.append((WIRE_PATH,
                           f"give-up path ticked {sorted(tm.counters)} — "
                           f"expected wire.retry and wire.giveup counts"))
        evs = [e for e in tm.tail(8) if e["ev"] == wire.WIRE_EVENT]
        if not evs or evs[-1].get("kind") != "giveup":
            errors.append((WIRE_PATH,
                           f"give-up emitted no {wire.WIRE_EVENT!r} event "
                           f"with kind='giveup'"))

    # 2. dedup window: a replayed token must be a hit that ticks the
    # declared counter and does NOT read as fresh
    tm2 = telemetry.Telemetry(rank=0, run_id="drift-check")
    win = wire.DedupWindow(telemetry_=tm2)
    tok = {"w": "drift", "seq": 0}
    dup, _ = win.check(tok, "push")
    win.record(tok, "push", {"ok": True})
    dup2, _ = win.check(tok, "push")
    if dup or not dup2 or win.hits != 1 or \
            tm2.counters.get("wire.dedup_hit", 0) != 1:
        errors.append((WIRE_PATH,
                       "DedupWindow replay did not register exactly one "
                       f"wire.dedup_hit (fresh={dup}, dup={dup2}, "
                       f"hits={win.hits})"))

    # 3. version mismatch fails LOUDLY with both versions in the message
    import socket as _socket
    a, b = _socket.socketpair()
    try:
        a.sendall(wire.encode_frame({"ok": True, "v": 999999}))
        try:
            wire.recv_msg(b)
            errors.append((WIRE_PATH,
                           "a version-mismatched frame did not raise"))
        except wire.VersionMismatch as e:
            msg = str(e)
            if "999999" not in msg or str(wire.WIRE_VERSION) not in msg:
                errors.append((WIRE_PATH,
                               f"VersionMismatch message lacks both "
                               f"versions: {msg!r}"))
    finally:
        a.close()
        b.close()

    # 4. the center-outage pair: a live controller must emit exactly
    # CENTER_EVENTS, and the report must consume them + the wire schema
    if membership is not None:
        tm3 = telemetry.Telemetry(rank=0, run_id="drift-check")
        ctl = membership.MembershipController(telemetry_=tm3)
        ctl.center_down(reason="probe")
        ctl.center_restored(attempt=1)
        got = {e["ev"] for e in tm3.tail(4) if e["ev"] != "run_start"}
        if got != set(membership.CENTER_EVENTS):
            errors.append((MEMBERSHIP_PATH,
                           f"a live controller's center outage pair "
                           f"emitted {sorted(got)} != CENTER_EVENTS "
                           f"{sorted(membership.CENTER_EVENTS)}"))
    if telemetry_report is not None:
        tracked = set(getattr(telemetry_report, "TRACKED_EVENTS", ()))
        want = {wire.WIRE_EVENT}
        if membership is not None:
            want |= set(getattr(membership, "CENTER_EVENTS", ()))
        missing = sorted(want - tracked)
        if missing:
            errors.append((REPORT_PATH,
                           f"TRACKED_EVENTS is missing wire/center event "
                           f"kind(s) {missing} — the chaos gate's "
                           "center_down→center_restored matching and the "
                           "wire outage markers would be dropped"))
        counters = set(getattr(telemetry_report, "TRACE_COUNTER_KEYS", ()))
        missing_g = sorted(set(wire.WIRE_GAUGES) - counters)
        if missing_g:
            errors.append((REPORT_PATH,
                           f"TRACE_COUNTER_KEYS is missing wire gauge(s) "
                           f"{missing_g} — the Perfetto export would not "
                           "render outage durations"))
    return errors


def tracing_schema_errors(tracing, telemetry,
                          telemetry_report=None) -> List[tuple]:
    """Round-16 probes: the causal-tracing span/statusz vocabulary
    (docs/design.md §17).  LIVE checks, all jax-free:

    * a Tracer driven through a round must emit a ``span`` event carrying
      every declared :data:`SPAN_FIELDS` key;
    * the three span emitters (round via ``Tracer``, ``emit_wire_span``,
      ``emit_server_span``) fed into the REPORT's trace assembly must
      produce one joined round whose critical-path components sum to the
      round time, with a dedup twin counted but never joined — a span
      emitter the report cannot render fails the gate here;
    * a live :class:`StatuszServer` must answer a real socket ``health``
      query with every declared :data:`STATUSZ_FIELDS` key and register/
      deregister its discovery doc;
    * the report must track ``span``/``statusz`` and agree on the
      component vocabulary."""
    errors: List[tuple] = []
    if tracing is None:
        return errors

    # 1. a live round span carries the declared field set
    tm = telemetry.Telemetry(rank=0, run_id="drift-check")
    tr = tracing.Tracer(telemetry_=tm)
    rnd = tr.begin("round", island=0)
    ctx = rnd.ctx()
    rnd.end(outcome="exchanged")
    spans = [e for e in tm.tail(4) if e["ev"] == tracing.SPAN_EVENT]
    if not spans:
        errors.append((TRACING_PATH,
                       "a live Tracer round emitted no "
                       f"{tracing.SPAN_EVENT!r} event"))
    else:
        missing = [k for k in tracing.SPAN_FIELDS
                   if k not in spans[-1] and k != "parent"]
        if missing:                      # parent is None → omitted is fine
            errors.append((TRACING_PATH,
                           f"round span event lacks declared SPAN_FIELDS "
                           f"{missing}: {sorted(spans[-1])}"))
        if tr.spans != 1:
            errors.append((TRACING_PATH,
                           f"Tracer.spans counted {tr.spans} after one "
                           "emitted span"))

    # 2. the full client+server emitter set must assemble into ONE joined
    # round in the live report — with the dedup twin tagged, counted, and
    # never double-counted on the critical path
    import time as _time
    tm2 = telemetry.Telemetry(rank=1, run_id="drift-check")
    tr2 = tracing.Tracer(telemetry_=tm2)
    rnd2 = tr2.begin("round", island=1)
    wire_ctx = rnd2.ctx()
    sid = tracing.new_span_id()
    tracing.emit_wire_span(tm2, wire_ctx, "push", span=sid,
                           t0=rnd2.t0, dt=0.01, q=0.002, a=0.003)
    srv_ctx = {"t": rnd2.trace, "s": sid}
    tracing.emit_server_span(tm2, srv_ctx, "push", t0=rnd2.t0, dt=0.006,
                             q=0.002, a=0.003, island=1)
    tracing.emit_server_span(tm2, srv_ctx, "push", t0=rnd2.t0, dt=0.0001,
                             island=1, dedup=True)
    _time.sleep(0.015)          # round dt must cover its wire op's 10ms
    rnd2.end(outcome="exchanged")
    if telemetry_report is not None:
        assemble = getattr(telemetry_report, "assemble_traces", None)
        if assemble is None:
            errors.append((REPORT_PATH,
                           "telemetry_report has no assemble_traces — "
                           "span events would be emitted but never "
                           "joined/rendered"))
        else:
            traces = assemble(tm2.tail(8))
            if len(traces) != 1:
                errors.append((REPORT_PATH,
                               f"trace assembly built {len(traces)} "
                               "round(s) from one emitted round"))
            else:
                t = traces[0]
                if t["joined"] != 1 or t["dedup_twins"] != 1:
                    errors.append((REPORT_PATH,
                                   f"client span did not join exactly one "
                                   f"applied server span with one dedup "
                                   f"twin (joined={t['joined']}, "
                                   f"twins={t['dedup_twins']})"))
                total = sum(t["components"].values())
                if abs(total - t["dt"]) > max(0.05 * t["dt"], 1e-6):
                    errors.append((REPORT_PATH,
                                   f"critical-path components sum "
                                   f"{total:.6f} != round dt "
                                   f"{t['dt']:.6f}"))
                if set(t["components"]) != set(tracing.COMPONENTS):
                    errors.append((REPORT_PATH,
                                   f"component vocabulary "
                                   f"{sorted(t['components'])} != "
                                   f"tracing.COMPONENTS "
                                   f"{sorted(tracing.COMPONENTS)}"))
        comps = getattr(telemetry_report, "TRACE_COMPONENTS", ())
        if tuple(comps) != tuple(tracing.COMPONENTS):
            errors.append((REPORT_PATH,
                           f"TRACE_COMPONENTS {tuple(comps)!r} != "
                           f"tracing.COMPONENTS "
                           f"{tuple(tracing.COMPONENTS)!r}"))
        tracked = set(getattr(telemetry_report, "TRACKED_EVENTS", ()))
        missing = sorted({tracing.SPAN_EVENT,
                          tracing.STATUSZ_EVENT} - tracked)
        if missing:
            errors.append((REPORT_PATH,
                           f"TRACKED_EVENTS is missing tracing event "
                           f"kind(s) {missing} — spans/statusz would be "
                           "silently dropped from report and trace"))

    # 3. a live statusz endpoint answers with the declared field set and
    # registers/deregisters its discovery doc
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        tm3 = telemetry.Telemetry(rank=0, run_id="drift-check")
        sz = tracing.StatuszServer("probe", ident=0, run_dir=d,
                                   telemetry_=tm3, tracer_=tr)
        try:
            host, port = sz.start()
            docs = tracing.read_statusz_docs(d)
            if len(docs) != 1 or docs[0].get("port") != port:
                errors.append((TRACING_PATH,
                               f"statusz discovery doc missing/wrong "
                               f"under {d}: {docs}"))
            rep = tracing.statusz_query(f"{host}:{port}", "health")
            missing = [k for k in tracing.STATUSZ_FIELDS if k not in rep]
            if missing:
                errors.append((TRACING_PATH,
                               f"statusz health reply lacks declared "
                               f"STATUSZ_FIELDS {missing}: "
                               f"{sorted(rep)}"))
            evs = tracing.statusz_query(f"{host}:{port}", "events", n=4)
            if not evs.get("ok") or "events" not in evs:
                errors.append((TRACING_PATH,
                               "statusz events op returned no event "
                               "list"))
            sz_evs = [e for e in tm3.tail(4)
                      if e["ev"] == tracing.STATUSZ_EVENT]
            if not sz_evs or "addr" not in sz_evs[-1]:
                errors.append((TRACING_PATH,
                               f"statusz start emitted no "
                               f"{tracing.STATUSZ_EVENT!r} event with an "
                               f"addr"))
        except Exception as e:
            errors.append((TRACING_PATH,
                           f"live statusz probe failed: {e!r}"))
        finally:
            sz.stop()
        if tracing.read_statusz_docs(d):
            errors.append((TRACING_PATH,
                           "statusz stop() left its discovery doc "
                           "behind — fleetz would list a ghost"))
    return errors


def fleetmon_schema_errors(fleetmon, membership, telemetry,
                           telemetry_report=None) -> List[tuple]:
    """Round-18 probes: the fleet-health vocabulary (docs/design.md
    §20).  LIVE checks, all jax-free:

    * the stock rule sets pass their own grammar validator, and every
      rule name :data:`FAULT_ALERT_COVERAGE` promises the alert-audit
      exists in the full stock set — a renamed rule would silently
      vacate the audit;
    * a live collector fed a breaching sample fires EXACTLY ONE
      ``alert`` event carrying rule/series/worker/value/threshold, and
      does NOT re-fire while the breach persists (the no-flapping
      episode contract IS schema);
    * a demote-actioned alert driven through :func:`fleetmon.apply_alert`
      lands a ``worker_demote`` event CITING the firing rule by name,
      and that name exists in the rule set;
    * the text exposition covers every registered fleet series;
    * the report tracks the ``alert`` event kind."""
    errors: List[tuple] = []
    if fleetmon is None:
        return errors

    # 1. rule grammar: the stock sets must validate, and the audit's
    # coverage map must name real rules (the FULL set — step_time rules
    # are opt-in by threshold)
    try:
        fleetmon.validate_rules(fleetmon.DEFAULT_RULES)
        full = fleetmon.validate_rules(fleetmon.default_rules(
            step_p99_s=1.0, hbm_headroom_bytes=1.0, divergence=1.0))
    except ValueError as e:
        errors.append((FLEETMON_PATH,
                       f"the stock rule set fails its own validator: {e}"))
        full = []
    full_names = {r["name"] for r in full}
    for kind, names in fleetmon.FAULT_ALERT_COVERAGE.items():
        missing = sorted(set(names) - full_names)
        if missing:
            errors.append((FLEETMON_PATH,
                           f"FAULT_ALERT_COVERAGE[{kind!r}] names rule(s) "
                           f"{missing} absent from default_rules(...) — "
                           "the alert-audit for that fault kind is "
                           "vacuously uncovered"))

    # 2. a live breach fires exactly one schema-complete alert event,
    # and holds (no flapping) while the breach persists
    tm = telemetry.Telemetry(rank=0, run_id="drift-check")
    rules = [{"name": "probe_rule", "series": "step_p99",
              "predicate": "threshold", "op": ">", "value": 1.0,
              "scope": "rank"}]
    col = fleetmon.FleetCollector(rules=rules, telemetry_=tm)
    col.ingest({"step_p99": 5.0}, rank=3)
    first = col.evaluate()
    col.ingest({"step_p99": 6.0}, rank=3)
    second = col.evaluate()
    evs = [e for e in tm.tail(8) if e["ev"] == fleetmon.ALERT_EVENT]
    if len(first) != 1 or len(evs) != 1:
        errors.append((FLEETMON_PATH,
                       f"one breaching sample fired {len(first)} alert(s) "
                       f"/ {len(evs)} event(s) — expected exactly 1"))
    elif second:
        errors.append((FLEETMON_PATH,
                       "a persisting breach RE-fired on the next "
                       "evaluation — the no-flapping episode contract "
                       "is broken"))
    else:
        ev = evs[-1]
        missing = [k for k in ("rule", "series", "worker", "value",
                               "threshold") if k not in ev]
        if missing:
            errors.append((FLEETMON_PATH,
                           f"alert event lacks field(s) {missing}: "
                           f"{sorted(ev)}"))

    # 3. an alert-driven demotion cites a real rule name in the
    # worker_demote event (the §20 closed loop)
    if membership is not None:
        tm2 = telemetry.Telemetry(rank=0, run_id="drift-check")
        ctl = membership.MembershipController(telemetry_=tm2)
        ctl.join(1, pid=1)
        ctl.join(2, pid=2)
        alert = {"rule": "probe_rule", "series": "step_p99",
                 "rank": 1, "value": 5.0, "threshold": 1.0,
                 "action": "demote"}
        if not fleetmon.apply_alert(ctl, alert):
            errors.append((FLEETMON_PATH,
                           "apply_alert did not demote a live worker"))
        else:
            demotes = [e for e in tm2.tail(8)
                       if e["ev"] == "worker_demote"]
            if not demotes or demotes[-1].get("rule") != "probe_rule":
                errors.append((FLEETMON_PATH,
                               f"alert-driven worker_demote does not "
                               f"cite the firing rule: "
                               f"{demotes[-1] if demotes else None}"))
            elif demotes[-1]["rule"] not in {r["name"] for r in
                                             col.rules}:
                errors.append((FLEETMON_PATH,
                               f"demote cites rule "
                               f"{demotes[-1]['rule']!r} that exists in "
                               "no active rule set"))

    # 4. the exposition covers every registered fleet series
    col2 = fleetmon.FleetCollector(rules=[], telemetry_=telemetry.DISABLED)
    col2.ingest({k: 1.0 for k in fleetmon.METRIC_FIELDS}, rank=0)
    text = col2.expose_text()
    missing = [s for s in fleetmon.FLEET_SERIES
               if ("theanompi_" + s) not in text]
    if missing:
        errors.append((FLEETMON_PATH,
                       f"expose_text() omits registered fleet series "
                       f"{missing} — a scrape would silently miss them"))

    # 5. the report consumes the alert vocabulary
    if telemetry_report is not None:
        tracked = set(getattr(telemetry_report, "TRACKED_EVENTS", ()))
        missing = sorted(set(fleetmon.ALERT_EVENTS) - tracked)
        if missing:
            errors.append((REPORT_PATH,
                           f"TRACKED_EVENTS is missing fleet-health "
                           f"event kind(s) {missing} — alerts would be "
                           "dropped from report and Perfetto export"))
    return errors


def numerics_schema_errors(numerics, sentry, fleetmon, telemetry,
                           telemetry_report=None) -> List[tuple]:
    """Round-25 probes: the numerics health plane (docs/design.md §25).
    LIVE, jax-free (the host-plane half of ``utils/numerics`` is
    stdlib-only by contract):

    * the sentry kinds the plane raises are declared anomaly kinds;
    * a live ``record(example_report())`` emits EVERY declared
      ``NUMERICS_GAUGES`` gauge, every ``NUMERICS_HISTOGRAMS``
      distribution, and exactly one ``NUMERICS_EVENT`` event;
    * a live sentry fed an overflowing report raises ``grad_overflow``
      through the real anomaly event path;
    * fleetmon's snapshot schema carries the beacon series the
      ``replica_divergence`` rule reads;
    * the report/trace converter consumes the event kind and renders
      the divergence/grad-norm counter tracks."""
    errors: List[tuple] = []
    if numerics is None:
        return errors

    if sentry is not None:
        missing = sorted(set(numerics.SENTRY_KINDS) -
                         set(sentry.ANOMALY_KINDS))
        if missing:
            errors.append((NUMERICS_PATH,
                           f"numerics SENTRY_KINDS {missing} absent from "
                           f"sentry.ANOMALY_KINDS — the detectors would "
                           "raise undeclared anomalies"))

    # a live record() must cover the whole declared gauge/event surface
    tm = telemetry.Telemetry(rank=0, run_id="drift-check")
    rep = numerics.example_report()
    numerics.record(tm, rep, rank=0)
    missing = sorted(set(numerics.NUMERICS_GAUGES) - set(tm.gauges))
    if missing:
        errors.append((NUMERICS_PATH,
                       f"record(example_report()) never set declared "
                       f"gauge(s) {missing}"))
    missing = sorted(set(numerics.NUMERICS_HISTOGRAMS) - set(tm.hists))
    if missing:
        errors.append((NUMERICS_PATH,
                       f"record(example_report()) never observed declared "
                       f"histogram(s) {missing}"))
    evs = [e for e in tm.tail(4) if e["ev"] == numerics.NUMERICS_EVENT]
    if len(evs) != 1:
        errors.append((NUMERICS_PATH,
                       f"record(example_report()) emitted {len(evs)} "
                       f"{numerics.NUMERICS_EVENT!r} event(s) — "
                       "expected exactly 1"))

    # a live sentry fed an overflow raises through the real event path
    if sentry is not None:
        tm2 = telemetry.Telemetry(rank=0, run_id="drift-check")
        s = sentry.TrainingSentry({"verbose": False}, telemetry=tm2)
        bad = dict(numerics.example_report())
        bad["nonfinite"] = 4.0
        kind = s.observe_numerics(bad)
        anoms = [e for e in tm2.tail(4)
                 if e["ev"] == sentry.ANOMALY_EVENT]
        if kind != "grad_overflow" or not anoms:
            errors.append((SENTRY_PATH,
                           "an overflowing numerics report did not raise "
                           f"a live grad_overflow anomaly (got {kind!r})"))

    # fleetmon's snapshot schema must carry the beacon series
    if fleetmon is not None:
        missing = sorted({"grad_norm", "divergence"} -
                         set(fleetmon.METRIC_FIELDS))
        if missing:
            errors.append((FLEETMON_PATH,
                           f"METRIC_FIELDS is missing numerics series "
                           f"{missing} — the replica_divergence rule "
                           "would read an unregistered series"))

    # the report consumes the event + renders the counter tracks
    if telemetry_report is not None:
        tracked = set(getattr(telemetry_report, "TRACKED_EVENTS", ()))
        if numerics.NUMERICS_EVENT not in tracked:
            errors.append((REPORT_PATH,
                           f"TRACKED_EVENTS is missing "
                           f"{numerics.NUMERICS_EVENT!r} — numerics "
                           "reports would be dropped from the report"))
        counters = set(getattr(telemetry_report,
                               "TRACE_COUNTER_KEYS", ()))
        missing = sorted({"numerics.grad_norm", "numerics.divergence"} -
                         counters)
        if missing:
            errors.append((REPORT_PATH,
                           f"TRACE_COUNTER_KEYS is missing numerics "
                           f"key(s) {missing} — the Perfetto export "
                           "would drop the counter tracks"))
    return errors


def key_extra_schema_errors(compile_cache_mod=None,
                            root: Optional[str] = None) -> List[tuple]:
    """Round-26 probe: the cache-key checker's statically-extracted
    ``key_extra`` stamp vocabulary must equal the keys a REAL
    ``key_extra`` run stamps (the stamping call every compile surface —
    ``compile_iter_fns``, bench, prewarm — goes through), and both must
    equal the checker's ``STAMP_KNOBS`` coverage registry — so neither
    the extraction rules nor the registry can go stale (the PR 15
    center-protocol precedent).  jax-free by construction:
    ``compile_cache`` keeps jax out of module scope, the probe config
    pins ``ushard_min_bytes`` so the ushard branch never imports
    ``update_sharding``, and ``THEANOMPI_TPU_NO_PALLAS`` is forced for
    the maximal call.  Also pins the §26 byte-stability floor: a
    knob-less ``key_extra("val")`` must stay exactly ``{"fn": "val"}``."""
    from ..core import SourceFile
    from .compile_surface import (COMPILE_CACHE_PATH, STAMP_KNOBS,
                                  key_extra_vocabulary)
    errors: List[tuple] = []
    if compile_cache_mod is None:
        try:
            from theanompi_tpu.utils import compile_cache as \
                compile_cache_mod
        except ImportError:
            return errors
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
    if not os.path.exists(os.path.join(root, COMPILE_CACHE_PATH)):
        return errors
    try:
        sf = SourceFile(root, COMPILE_CACHE_PATH)
    except (OSError, SyntaxError, ValueError):
        return errors            # the parse step reports it already
    static_stamps, _knobs, _problems = key_extra_vocabulary(sf)

    # a maximal probe call: every guarded stamp switched on at once
    class _ProbeStrategy:
        name = "probe"

    class _ProbeExchanger:
        strategy = _ProbeStrategy()
        mode = "params"
        exchange_freq = 2
        bucket_bytes = 1 << 20

    class _ProbeModel:
        n_subb = 2
        pp_interleave = 2
        _fsdp = None
        config = {"numerics": True, "update_sharding": True,
                  "ushard_min_bytes": 4096}

    # the whole probe pins THEANOMPI_TPU_NO_PALLAS — "1" for the
    # maximal call, absent for the byte-stability floor — so the
    # verdict (which the result cache stores keyed on file contents)
    # never depends on whatever the host process happens to export
    saved = os.environ.get("THEANOMPI_TPU_NO_PALLAS")
    os.environ["THEANOMPI_TPU_NO_PALLAS"] = "1"
    try:
        try:
            live = compile_cache_mod.key_extra(
                "train", model=_ProbeModel(),
                exchanger=_ProbeExchanger(), spc=3)
        except Exception as e:
            return [(COMPILE_CACHE_PATH,
                     f"the maximal jax-free key_extra probe call raised "
                     f"{e!r} — the stamping path must stay callable "
                     f"without a backend")]
        os.environ.pop("THEANOMPI_TPU_NO_PALLAS", None)
        base = compile_cache_mod.key_extra("val")
    finally:
        if saved is None:
            os.environ.pop("THEANOMPI_TPU_NO_PALLAS", None)
        else:
            os.environ["THEANOMPI_TPU_NO_PALLAS"] = saved

    if set(static_stamps) != set(live):
        errors.append((COMPILE_CACHE_PATH,
                       f"statically-extracted key_extra stamps "
                       f"{sorted(static_stamps)} != keys a maximal live "
                       f"key_extra run stamped {sorted(live)} — the "
                       "cache-key checker's extraction rules drifted"))
    if set(live) != set(STAMP_KNOBS):
        errors.append((COMPILE_CACHE_PATH,
                       f"live key_extra stamps {sorted(live)} != the "
                       f"cache-key checker's STAMP_KNOBS registry "
                       f"{sorted(STAMP_KNOBS)} — declare (or drop) the "
                       "coverage entry in "
                       "analysis/checkers/compile_surface.py"))

    # §26 byte-stability floor: knob-less extras are frozen
    if base != {"fn": "val"}:
        errors.append((COMPILE_CACHE_PATH,
                       f"key_extra('val') returned {base!r} — a "
                       "knob-less config's extras must stay exactly "
                       "{'fn': 'val'} so every pre-existing cache key "
                       "is byte-stable"))
    return errors


def thread_role_coverage_errors(root: Optional[str] = None) -> List[tuple]:
    """Round-15 probe: the host-concurrency pass is only as good as its
    thread-role map, so every ``threading.Thread(...)``/``Timer(...)``
    construction in the thread-heaviest runtime modules
    (``membership.py``, ``chaos.py``) must (a) appear among
    ``engine.spawn_sites()`` and (b) RESOLVE to its entry function — a
    spawn whose target the engine cannot resolve silently escapes the
    shared-state-race/daemon-discipline analysis.  Built live on a mini
    ProgramIndex over just those files, so a new spawn idiom the
    resolver does not understand fails the gate the day it lands."""
    import ast as _ast

    from ..core import SourceFile
    from ..engine import ProgramIndex
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
    files = []
    for rel in (MEMBERSHIP_PATH, CHAOS_PATH):
        full = os.path.join(root, rel)
        if os.path.exists(full):
            try:
                files.append(SourceFile(root, rel))
            except SyntaxError:
                continue           # the parse step reports it already
    if not files:
        return []
    index = ProgramIndex(files)
    sites = {}
    for s in index.spawn_sites():
        if s.kind in ("thread", "timer"):
            sites[(s.path, s.line)] = s
    errors: List[tuple] = []
    for sf in files:
        for node in _ast.walk(sf.tree):
            if not isinstance(node, _ast.Call):
                continue
            resolved = sf.resolver.resolve(node.func)
            if resolved not in ("threading.Thread", "threading.Timer"):
                continue
            site = sites.get((sf.path, node.lineno))
            if site is None:
                errors.append((sf.path,
                               f"thread spawn at line {node.lineno} is "
                               f"invisible to the thread-role map "
                               f"(engine.spawn_sites) — the "
                               f"host-concurrency pass cannot analyze "
                               f"it"))
            elif not site.entries:
                errors.append((sf.path,
                               f"thread spawn at line {node.lineno} "
                               f"(target `{site.target_desc}`) does not "
                               f"resolve to an entry function — its "
                               f"thread role is empty and its body "
                               f"escapes the race analysis"))
    return errors


def center_protocol_errors(center_server, root: Optional[str] = None
                           ) -> List[tuple]:
    """Round-19 probe: the §21 protocol model cross-checked against the
    RUNTIME client surface.  The op table statically extracted from the
    center dispatch ladder must be exactly (a) the op set the static
    client table sees RemoteCenter sending AND (b) the ops a LIVE
    RemoteCenter actually puts on the wire when every public op method
    is driven against a stubbed wire — so neither the extraction rules
    nor the client can drift from the runtime surface unnoticed.  The
    wire stub captures each request header and aborts before any
    network or jax work (``_leaves`` is stubbed too), keeping the probe
    socket-free and backend-free."""
    from .. import protocol
    from ..core import SourceFile
    from ..engine import ProgramIndex
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
    if not os.path.exists(os.path.join(root, protocol.CENTER_PATH)):
        return []
    try:
        sf = SourceFile(root, protocol.CENTER_PATH)
    except (SyntaxError, OSError):
        return []               # the parse step reports it already
    index = ProgramIndex([sf])
    spec = next(s for s in protocol.ENDPOINTS if s.name == "center")
    table = protocol.server_op_table(index, spec)
    errors: List[tuple] = []
    if table is None:
        errors.append((CENTER_PATH,
                       "the §21 center op table could not be extracted "
                       "(dispatch function missing?) — the protocol "
                       "checkers are blind to this endpoint"))
        return errors
    static_server = set(table)
    static_client = set(protocol.client_op_table(index, spec))

    class _Captured(Exception):
        pass

    sent: set = set()

    class _WireStub:
        def request(self, header, body=b"", trace=None):
            sent.add(header.get("op"))
            raise _Captured()

        def close(self):
            pass

    rc = center_server.RemoteCenter("127.0.0.1:9")
    try:
        rc._wire.close()
    except OSError:
        pass
    rc._wire = _WireStub()
    rc._leaves = lambda tree: ([], None)    # instance stub: no jax flatten
    surface = (("ensure_init", (None,)), ("pull", ()),
               ("pull_leaves", ()), ("push_delta", (None, 0)),
               ("push_pull", (None, 0)), ("demote_island", (0,)),
               ("readmit_island", (0,)), ("stats", ()))
    for method, args in surface:
        try:
            getattr(rc, method)(*args)
        except _Captured:
            continue
        except Exception as e:
            errors.append((CENTER_PATH,
                           f"RemoteCenter.{method} failed before "
                           f"reaching the wire ({e!r}) — the runtime "
                           "surface probe cannot see its op"))
    if sent != static_server:
        errors.append((CENTER_PATH,
                       f"a live RemoteCenter sends ops {sorted(sent)} "
                       f"!= the extracted center dispatch table "
                       f"{sorted(static_server)} — static protocol "
                       "view drifted from the runtime surface (or this "
                       "probe's own hardcoded `surface` method list in "
                       "center_protocol_errors is stale: extend it "
                       "when adding an op)"))
    if static_client != static_server:
        errors.append((CENTER_PATH,
                       f"the static client op table "
                       f"{sorted(static_client)} != the extracted "
                       f"dispatch table {sorted(static_server)} — the "
                       "wire-contract checker should have caught this; "
                       "its extraction rules drifted"))
    return errors


def _load_parallel(name: str):
    """A ``theanompi_tpu.parallel`` submodule imported WITHOUT executing
    the jax-importing package ``__init__``: when the real package is not
    already loaded, a synthetic parent (the scripts/lint.py bootstrap
    pattern) is registered so the submodule's relative imports
    (``from . import wire``) resolve jax-free.  None when absent or
    broken (the probe skips its cross-checks)."""
    import importlib
    import importlib.machinery
    import types
    full = f"theanompi_tpu.parallel.{name}"
    if full in sys.modules:
        return sys.modules[full]
    if "theanompi_tpu.parallel" not in sys.modules:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        pkg_dir = os.path.join(root, "theanompi_tpu", "parallel")
        if not os.path.isdir(pkg_dir):
            return None
        pkg = types.ModuleType("theanompi_tpu.parallel")
        pkg.__path__ = [pkg_dir]
        spec = importlib.machinery.ModuleSpec(
            "theanompi_tpu.parallel", loader=None, is_package=True)
        spec.submodule_search_locations = [pkg_dir]
        pkg.__spec__ = spec
        sys.modules["theanompi_tpu.parallel"] = pkg
    try:
        return importlib.import_module(full)
    except Exception:
        return None


def _load_by_path(relpath: str, name: str):
    """A probed module loaded by FILE path — for modules that are not
    importable in the lint CLI's jax-free process through the synthetic
    package (scripts are not package modules; ``parallel/__init__``
    imports jax, so ``parallel/membership.py`` — itself stdlib-only at
    module scope by contract — loads this way too).  None when absent or
    broken (the parse step flags a syntax error as a normal finding; the
    probe just skips its cross-checks)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    path = os.path.join(root, relpath)
    if not os.path.exists(path):
        return None
    import importlib.util
    try:
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception:
        return None
    return mod


def _load_telemetry_report():
    return _load_by_path(os.path.join("scripts", "telemetry_report.py"),
                         "_tpulint_telemetry_report")


@register
class SchemaDriftChecker(Checker):
    name = "schema-drift"
    description = ("recorder.SECTIONS / print_train_info record keys / "
                   "telemetry phase events must derive from telemetry."
                   "PHASES; device.* gauges, sentry anomaly schema, and "
                   "bench trace columns must match their declared "
                   "vocabularies (live-object probe)")
    reads_files = False    # `--only schema-drift` skips the repo parse
    # every file the live probes load beyond the lint selection — the
    # runner folds these into partial runs' cache keys (core.Checker)
    disk_scoped = (RECORDER_PATH, TELEMETRY_PATH, DEVPROF_PATH,
                   SENTRY_PATH, REPORT_PATH, MEMBERSHIP_PATH,
                   CHAOS_PATH, WIRE_PATH, TRACING_PATH, FLEETMON_PATH,
                   CENTER_PATH, NUMERICS_PATH, COMPILE_CACHE_PATH)

    def check_project(self, files):
        # normal import both under pytest (real package loaded) and under
        # the lint CLI (scripts/lint.py registers a synthetic
        # `theanompi_tpu` parent whose __path__ skips the jax-importing
        # package __init__)
        from theanompi_tpu.utils import recorder, telemetry
        errors = live_drift_errors(recorder, telemetry)
        try:
            # absent from a partial tree (precommit_lint.sh lints staged
            # blobs — a restricted checkout may omit them): the device
            # probes are skipped, the phase probes above still ran
            from theanompi_tpu.utils import devprof, sentry
        except ImportError:
            devprof = sentry = None
        report = _load_telemetry_report()
        if devprof is not None and sentry is not None:
            errors += device_schema_errors(devprof, sentry, telemetry,
                                           report)
        # membership/chaos by file path: parallel/__init__ imports jax,
        # which the lint CLI's no-backend contract forbids
        membership = _load_by_path(
            os.path.join("theanompi_tpu", "parallel", "membership.py"),
            "_tpulint_membership")
        chaos = _load_by_path(
            os.path.join("theanompi_tpu", "utils", "chaos.py"),
            "_tpulint_chaos")
        errors += membership_schema_errors(membership, chaos, telemetry,
                                           report)
        # round 14: the resilient-RPC wire layer (stdlib+numpy at module
        # scope by contract — file-path loads jax-free like membership)
        wire = _load_by_path(
            os.path.join("theanompi_tpu", "parallel", "wire.py"),
            "_tpulint_wire")
        errors += wire_schema_errors(wire, membership, telemetry, report)
        # round 16: the causal-tracing span/statusz vocabulary — live
        # emitters joined through the live report, statusz on a real
        # socket (utils/tracing is stdlib-only by contract, importable
        # through the synthetic package like telemetry)
        try:
            from theanompi_tpu.utils import tracing as tracing_mod
        except ImportError:
            tracing_mod = None
        errors += tracing_schema_errors(tracing_mod, telemetry, report)
        # round 18: the fleet-health plane — rule grammar, alert event
        # schema + no-flapping, rule-cited demotions, exposition
        # coverage (utils/fleetmon is stdlib-only by contract,
        # importable through the synthetic package like telemetry)
        try:
            from theanompi_tpu.utils import fleetmon as fleetmon_mod
        except ImportError:
            fleetmon_mod = None
        errors += fleetmon_schema_errors(fleetmon_mod, membership,
                                         telemetry, report)
        # round 25: the numerics health plane — sentry-kind vocabulary,
        # live record() gauge/event coverage, live grad_overflow raise,
        # beacon series in the fleetmon snapshot schema, report/trace
        # consumption (utils/numerics keeps jax out of module scope by
        # contract, importable through the synthetic package)
        try:
            from theanompi_tpu.utils import numerics as numerics_mod
        except ImportError:
            numerics_mod = None
        errors += numerics_schema_errors(numerics_mod, sentry,
                                         fleetmon_mod, telemetry, report)
        # round 19: the §21 protocol model cross-checked live — the
        # extracted center op table must equal the ops a real
        # RemoteCenter sends (static view vs runtime surface; the
        # parallel package parent is synthesized so the submodule
        # imports jax-free)
        center_server = _load_parallel("center_server")
        if center_server is not None:
            errors += center_protocol_errors(center_server)
        # round 15: the thread-role map must see and resolve every
        # Thread/Timer spawn in the thread-heaviest runtime modules
        errors += thread_role_coverage_errors()
        # round 26: the key_extra stamp vocabulary, static extraction vs
        # a real (jax-free) stamping run vs the cache-key checker's
        # coverage registry
        errors += key_extra_schema_errors()
        return [Finding(self.name, path, 1, 0, msg)
                for path, msg in errors]
