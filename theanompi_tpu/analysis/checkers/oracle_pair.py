"""oracle-pair: every Pallas kernel in ops/ keeps its jnp oracle honest.

The house kernel pattern (docs/design.md §24, ops/compress.py module
docstring) is a PAIR: a ``pl.pallas_call`` wrapper plus a pure-jnp
oracle with the identical bit layout, registered in the module's
``PALLAS_ORACLES`` dict and pinned equal by an interpret-mode test.
The oracle is not documentation — it IS the non-TPU dispatch target
(``_pallas_util.dispatch_pallas``), so an unregistered kernel is a
kernel whose CPU/forced-oracle path silently diverges from what TPUs
run, and an untested pair is a bit-layout contract nobody checks.

This checker closes the loop statically, jax-free:

* every function in ``theanompi_tpu/ops/*.py`` that issues a
  ``pl.pallas_call`` must have an entry in that module's top-level
  ``PALLAS_ORACLES`` dict (a pure literal, parsed with
  ``ast.literal_eval``);
* the named oracle must be a function defined in the same module;
* some file under ``tests/`` must reference BOTH names (the
  interpret-mode equality test — matched lexically by word boundary);
* a registry entry naming a function with no ``pl.pallas_call`` is
  stale and flagged too, so the dict cannot rot into folklore.

PROJECT-scoped on purpose: the ops modules and the test tree are read
from DISK (glob under the repo root), not from the file list the run
was invoked on — ``scripts/lint.py --diff`` passes only changed files,
and deleting a test must fail the gate even when no ops file changed.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Tuple

from ..core import Checker, Finding, SourceFile, register

OPS_GLOB = os.path.join("theanompi_tpu", "ops", "*.py")
TESTS_GLOB = os.path.join("tests", "*.py")
PALLAS_CALL = "jax.experimental.pallas.pallas_call"
REGISTRY_NAME = "PALLAS_ORACLES"


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


def _pallas_sites(sf: SourceFile) -> List[Tuple[Optional[str], int]]:
    """``(innermost enclosing function name, line)`` of every
    ``pl.pallas_call`` call in the module — resolved through the shared
    import resolver, so an aliased ``from jax.experimental import
    pallas as p`` still counts."""
    sites: List[Tuple[Optional[str], int]] = []

    def visit(node: ast.AST, fn: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            inner = fn
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner = child.name
            if isinstance(child, ast.Call) and \
                    sf.resolver.resolve(child.func) == PALLAS_CALL:
                sites.append((fn, child.lineno))
            visit(child, inner)

    visit(sf.tree, None)
    return sites


def _registry(sf: SourceFile) -> Tuple[Optional[Dict[str, str]], int]:
    """The module's top-level ``PALLAS_ORACLES`` literal and its line —
    ``(None, 1)`` when absent, ``(None, line)`` when present but not a
    pure ``{str: str}`` literal (flagged by the caller)."""
    for node in sf.tree.body:
        targets = node.targets if isinstance(node, ast.Assign) else \
            [node.target] if isinstance(node, ast.AnnAssign) else []
        if not any(isinstance(t, ast.Name) and t.id == REGISTRY_NAME
                   for t in targets):
            continue
        try:
            val = ast.literal_eval(node.value)
        except (ValueError, TypeError):
            return None, node.lineno
        if isinstance(val, dict) and all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in val.items()):
            return val, node.lineno
        return None, node.lineno
    return None, 1


def _module_functions(sf: SourceFile) -> set:
    return {n.name for n in ast.walk(sf.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _test_files_referencing(root: str) -> List[Tuple[str, str]]:
    """``(relpath, text)`` of every test module on disk."""
    out = []
    for path in sorted(glob.glob(os.path.join(root, TESTS_GLOB))):
        try:
            with open(path, encoding="utf-8") as f:
                out.append((os.path.relpath(path, root).replace(os.sep, "/"),
                            f.read()))
        except OSError:
            continue
    return out


def _referenced(name: str, texts: List[Tuple[str, str]]) -> List[str]:
    pat = re.compile(r"(?<![\w])%s(?![\w])" % re.escape(name))
    return [rel for rel, text in texts if pat.search(text)]


def oracle_pair_findings(root: str, check_name: str = "oracle-pair"
                         ) -> List[Finding]:
    """The whole audit, parameterized on the repo root so tests can run
    it against synthetic tmp_path trees (the schema_drift helper
    pattern)."""
    findings: List[Finding] = []
    tests = _test_files_referencing(root)
    for path in sorted(glob.glob(os.path.join(root, OPS_GLOB))):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            sf = SourceFile(root, rel)
        except (SyntaxError, OSError):
            continue       # the parse step reports it already
        sites = _pallas_sites(sf)
        registry, reg_line = _registry(sf)
        if not sites and registry is None:
            continue
        if registry is None:
            findings.append(Finding(
                check_name, rel, reg_line if reg_line > 1 else
                (sites[0][1] if sites else 1), 0,
                f"module issues pl.pallas_call but declares no "
                f"pure-literal {REGISTRY_NAME} dict mapping each "
                f"kernel wrapper to its jnp oracle"))
            continue
        defined = _module_functions(sf)
        wrappers = {fn for fn, _ in sites if fn}
        for fn, line in sites:
            if fn is None:
                findings.append(Finding(
                    check_name, rel, line, 0,
                    "pl.pallas_call at module scope — wrap it in a "
                    "function so it can be oracle-paired"))
            elif fn not in registry:
                findings.append(Finding(
                    check_name, rel, line, 0,
                    f"pl.pallas_call wrapper `{fn}` has no "
                    f"{REGISTRY_NAME} entry — its non-TPU dispatch "
                    f"path is unpinned"))
        for wrapper, oracle in sorted(registry.items()):
            if wrapper not in wrappers:
                findings.append(Finding(
                    check_name, rel, reg_line, 0,
                    f"{REGISTRY_NAME} entry `{wrapper}` names no "
                    f"function issuing pl.pallas_call in this module "
                    f"— stale registry entry"))
                continue
            if oracle not in defined:
                findings.append(Finding(
                    check_name, rel, reg_line, 0,
                    f"{REGISTRY_NAME} maps `{wrapper}` to `{oracle}`, "
                    f"which is not defined in this module"))
                continue
            if tests and not set(_referenced(wrapper, tests)) & \
                    set(_referenced(oracle, tests)):
                findings.append(Finding(
                    check_name, rel, reg_line, 0,
                    f"no tests/ file references both `{wrapper}` and "
                    f"`{oracle}` — the kernel/oracle bit-layout "
                    f"contract has no interpret-mode equality test"))
    return findings


@register
class OraclePairChecker(Checker):
    name = "oracle-pair"
    description = ("every pl.pallas_call wrapper in ops/ must be "
                   "registered in its module's PALLAS_ORACLES dict, the "
                   "named jnp oracle must exist in the same module, and "
                   "a tests/ file must reference both (interpret-mode "
                   "equality test) — the oracle is the non-TPU dispatch "
                   "target, so an unpaired kernel diverges silently")
    reads_files = False    # disk-scoped project probe: --diff safe
    # the audit reads ops/ and tests/ from disk regardless of the lint
    # selection — declaring them keys partial runs' result cache on
    # their content (core.Checker.disk_scoped)
    disk_scoped = (OPS_GLOB, TESTS_GLOB)

    def check_project(self, files) -> List[Finding]:
        return oracle_pair_findings(
            files[0].root if files else _repo_root(), self.name)
