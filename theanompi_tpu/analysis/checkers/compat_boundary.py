"""compat-boundary: shard_map/pvary/pcast go through ``jax_compat`` only.

The invariant (docs/design.md §12): the container may pin a jax where
``shard_map`` still lives in ``jax.experimental.shard_map`` and the vma
type system (``lax.pvary`` / ``lax.pcast``) does not exist — every call
site therefore routes through ``theanompi_tpu/jax_compat.py`` (the
shim) or ``steps._vary`` (the version-adaptive marker, which probes via
``getattr(lax, "pcast", ...)`` and is deliberately invisible to this
AST check).  A direct ``jax.shard_map`` / ``lax.pvary`` / ``lax.pcast``
reference anywhere else breaks the 0.4.x container even though it
imports fine on current jax — exactly the class of drift PR 1 recovered
tier-1 from.

Flagged: attribute references resolving to the banned dotted names, and
imports from ``jax.experimental.shard_map`` (the legacy location —
only the shim may touch it).  Name USES of a banned imported alias are
not re-flagged; the import line carries the finding.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Checker, Finding, SourceFile, register

BANNED = {
    "jax.shard_map",
    "jax.lax.pvary",
    "jax.lax.pcast",
}

LEGACY_MODULE = "jax.experimental.shard_map"

SHIM_PATH = "theanompi_tpu/jax_compat.py"


@register
class CompatBoundaryChecker(Checker):
    name = "compat-boundary"
    description = ("direct jax.shard_map/lax.pvary/lax.pcast references "
                   "outside jax_compat.py")

    def applies_to(self, path: str) -> bool:
        # the shim itself is the one sanctioned home of these names
        return not path.endswith("jax_compat.py")

    def check_file(self, sf: SourceFile):
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ImportFrom):
                base = sf.resolver.resolve_from_module(node)
                if base == LEGACY_MODULE or (
                        base and base.startswith(LEGACY_MODULE + ".")):
                    findings.append(Finding(
                        self.name, sf.path, node.lineno, node.col_offset,
                        f"import from `{LEGACY_MODULE}` outside "
                        "jax_compat.py — route through the shim "
                        "(theanompi_tpu.jax_compat.shard_map)"))
                    continue
                # `from jax import shard_map` / `from jax.lax import
                # pvary` bind the banned name without any Attribute node
                for a in (node.names if base else ()):
                    full = f"{base}.{a.name}"
                    if full in BANNED:
                        findings.append(Finding(
                            self.name, sf.path, node.lineno,
                            node.col_offset,
                            f"import of `{full}` outside jax_compat.py "
                            "— absent on the 0.4.x container; use "
                            "theanompi_tpu.jax_compat (shard_map) or "
                            "steps._vary (pvary/pcast)"))
                continue
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == LEGACY_MODULE or \
                            a.name.startswith(LEGACY_MODULE + "."):
                        findings.append(Finding(
                            self.name, sf.path, node.lineno,
                            node.col_offset,
                            f"import of `{a.name}` outside jax_compat.py "
                            "— route through the shim"))
                continue
            if isinstance(node, ast.Attribute):
                resolved = sf.resolver.resolve(node)
                if resolved in BANNED:
                    findings.append(Finding(
                        self.name, sf.path, node.lineno, node.col_offset,
                        f"direct `{resolved}` reference outside "
                        "jax_compat.py — absent on the 0.4.x container; "
                        "use theanompi_tpu.jax_compat (shard_map) or "
                        "steps._vary (pvary/pcast)"))
        return findings
