"""exchange-symmetry: every ``exchange_body`` issues the same collective
sequence on all paths.

The invariant (docs/design.md §12): an :class:`Exchanger` subclass's
``exchange_body`` is ONE pure per-worker function traced for every rank
— under multi-host SPMD each process traces its own copy, so a
rule-specific early return (or an if/else where only one arm reduces)
makes some ranks issue a collective others never reach: the program
deadlocks at the first mismatched collective, at run time, on the pod.
The fused in-scan cadence (``steps.build_train_step``'s ``lax.cond``)
makes this worse: the skipped collective is buried inside a compiled
multi-step dispatch.

Statically enforced shape: within ``exchange_body`` (every override in
the Exchanger hierarchy, found through the whole-program engine's class
graph),

* a collective-issuing expression — a direct ``lax`` collective or a
  call whose transitive summary issues collectives — must not sit under
  a Python ``if``/``else``/conditional expression unless BOTH arms
  issue the same collective multiset (``lax.cond``/``lax.switch`` are
  exempt: both branches are traced into the program);
* an early ``return``/``raise`` under a branch must not skip
  collective-issuing statements on the fall-through path.

Loops are allowed (static trip counts — uniform across ranks).
"""

from __future__ import annotations

import ast
from collections import Counter
from typing import List, Optional, Tuple

from ..core import Checker, Finding, register
from ..engine import FuncRecord, ProgramIndex, collective_name

EXCHANGER_ROOT = "theanompi_tpu.parallel.exchanger.Exchanger"
METHOD = "exchange_body"

_COND_WRAPPERS = {"jax.lax.cond", "jax.lax.switch"}


@register
class ExchangeSymmetryChecker(Checker):
    name = "exchange-symmetry"
    description = ("every Exchanger.exchange_body must issue the same "
                   "collective sequence on all paths — no early return "
                   "or one-armed branch around a collective")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        findings: List[Finding] = []
        for rec in self._exchange_bodies(index):
            self._check_body(index, rec, findings)
        return findings

    def _exchange_bodies(self, index: ProgramIndex) -> List[FuncRecord]:
        out: List[FuncRecord] = []
        seen = set()
        # every class whose ancestry reaches the Exchanger root (the
        # root's own exchange_body raises NotImplementedError — harmless)
        root_key = index._class_keys.get(EXCHANGER_ROOT)
        for rec in index.methods.get(METHOD, []):
            if rec.class_key is None or id(rec.node) in seen:
                continue
            keys = {rec.class_key}
            frontier = [rec.class_key]
            while frontier:
                k = frontier.pop()
                for b in index.class_bases.get(k, []):
                    if b == EXCHANGER_ROOT:
                        keys.add(root_key or k)
                    bk = index._class_keys.get(b)
                    if bk is not None and bk not in keys:
                        keys.add(bk)
                        frontier.append(bk)
            in_hierarchy = (root_key in keys if root_key is not None
                            else any(b == EXCHANGER_ROOT
                                     for k in keys
                                     for b in index.class_bases.get(k, [])))
            if in_hierarchy:
                seen.add(id(rec.node))
                out.append(rec)
        return out

    # -- analysis of one exchange_body -------------------------------------

    def _check_body(self, index: ProgramIndex, rec: FuncRecord,
                    findings: List[Finding]) -> None:
        self._index = index
        self._rec = rec
        body = rec.node.body if isinstance(rec.node.body, list) else []
        self._walk_block(body, findings)

    def _collectives_in_expr(self, expr: ast.AST) -> Counter:
        """Multiset of collective names this expression issues when
        evaluated: direct ``lax`` collectives plus resolvable calls whose
        transitive summary issues collectives.  ``lax.cond``/``switch``
        calls count as the UNION of their (traced-both) branches — a
        single uniform unit, not a divergence."""
        sf = self._rec.sf
        fidx = self._index.file_index[sf.path]
        out: Counter = Counter()
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                resolved = sf.resolver.resolve(node.func)
                cname = collective_name(resolved)
                if cname is not None:
                    out[cname] += 1
                elif resolved not in _COND_WRAPPERS:
                    enc = fidx.enclosing.get(id(node.func), self._rec.node)
                    for tgt in self._index.resolve_call(sf, node.func,
                                                        enc):
                        ts = self._index.transitive_summary(tgt)
                        for n in sorted(ts.collective_names):
                            out[n] += 1
                        break
            stack.extend(ast.iter_child_nodes(node))
        return out

    def _block_collectives(self, stmts: List[ast.stmt]) -> Counter:
        out: Counter = Counter()
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(st, ast.If):
                out += self._block_collectives(st.body)
                out += self._block_collectives(st.orelse)
                out += self._collectives_in_expr(st.test)
                continue
            for _, value in ast.iter_fields(st):
                if isinstance(value, ast.AST):
                    out += self._collectives_in_expr(value)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.stmt):
                            out += self._block_collectives([v])
                        elif isinstance(v, ast.AST):
                            out += self._collectives_in_expr(v)
        return out

    def _walk_block(self, stmts: List[ast.stmt],
                    findings: List[Finding],
                    after: Optional[Counter] = None) -> None:
        """``after`` = collectives issued AFTER this block returns to its
        parent (what an early exit here would skip)."""
        sf = self._rec.sf
        after = after if after is not None else Counter()
        # collectives issued by the statements following index i
        tails: List[Counter] = [Counter(after)]
        for st in reversed(stmts):
            tails.append(self._block_collectives([st]) + tails[-1])
        tails.reverse()          # tails[i] = everything from stmts[i] on

        for i, st in enumerate(stmts):
            rest = tails[i + 1]  # what follows this statement
            if isinstance(st, ast.If):
                arm_counts = (self._block_collectives(st.body),
                              self._block_collectives(st.orelse))
                arm_exits = (self._ends_flow(st.body),
                             self._ends_flow(st.orelse))
                # the collective multiset of the FULL PATH through each
                # arm: the arm's own collectives, plus — unless the arm
                # exits — everything after the if.  Any asymmetry is a
                # divergence, covering both an early return that SKIPS
                # later collectives and an exiting arm that ISSUES
                # collectives the fall-through never does.
                paths = tuple(
                    counts + (Counter() if exits else rest)
                    for counts, exits in zip(arm_counts, arm_exits))
                if paths[0] != paths[1]:
                    if arm_exits[0] or arm_exits[1]:
                        exiting = st.body if arm_exits[0] else st.orelse
                        node = exiting[-1] if exiting else st
                        findings.append(Finding(
                            self.name, sf.path, node.lineno,
                            node.col_offset,
                            f"early exit in `{self._rec.class_name}"
                            f".{METHOD}` diverges from the fall-through "
                            f"collective sequence: "
                            f"{dict(+paths[0]) or '{}'} vs "
                            f"{dict(+paths[1]) or '{}'} "
                            f"({', '.join(sorted((+paths[0]) + (+paths[1])))})"
                            " — all ranks must run the same collective "
                            "sequence"))
                    else:
                        findings.append(Finding(
                            self.name, sf.path, st.lineno, st.col_offset,
                            f"collective sequence diverges across `if` "
                            f"arms in `{self._rec.class_name}.{METHOD}`: "
                            f"{dict(arm_counts[0]) or '{}'} vs "
                            f"{dict(arm_counts[1]) or '{}'} — use "
                            "lax.cond (both branches traced) or issue "
                            "the same sequence in both arms"))
                # recurse for nested structure
                self._walk_block(st.body, findings, rest)
                self._walk_block(st.orelse, findings, rest)
                continue
            # conditional EXPRESSIONS with one-armed collectives
            for _, value in ast.iter_fields(st):
                values = value if isinstance(value, list) else [value]
                for v in values:
                    if not isinstance(v, ast.AST) or \
                            isinstance(v, ast.stmt):
                        continue
                    for sub in ast.walk(v):
                        if isinstance(sub, ast.IfExp):
                            a = self._collectives_in_expr(sub.body)
                            b = self._collectives_in_expr(sub.orelse)
                            if a != b:
                                findings.append(Finding(
                                    self.name, sf.path, sub.lineno,
                                    sub.col_offset,
                                    "collective sequence diverges "
                                    "across conditional-expression arms "
                                    f"in `{self._rec.class_name}"
                                    f".{METHOD}`: {dict(a) or '{}'} vs "
                                    f"{dict(b) or '{}'}"))
            # nested loop/with/try blocks
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(st, field, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt) and \
                        not isinstance(st, ast.If):
                    self._walk_block(sub, findings, rest)
            for h in getattr(st, "handlers", []):
                self._walk_block(h.body, findings, rest)

    @staticmethod
    def _ends_flow(stmts: List[ast.stmt]) -> bool:
        # Raise is deliberately NOT an exit here: an exception aborts
        # the whole process loudly (a config assert is uniform across
        # ranks), unlike a silent early return that keeps training with
        # a divergent collective sequence.
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Return, ast.Continue, ast.Break))
