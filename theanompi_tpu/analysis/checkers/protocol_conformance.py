"""Distributed-protocol conformance: the §15/§20 contracts, checked.

Three engine-scoped checkers over the declared endpoint model
(``analysis/protocol.py``, docs/design.md §21):

* **wire-contract** — diffs every endpoint's server op-dispatch table
  against its clients' sent-op table: a client op with no handler arm,
  a handler no in-repo client (or declared external surface) ever
  sends, a reply verdict the shared wire client inspects that no
  handler path sets (or emits that the client ignores), a ``retry:
  true`` reply that is not also ``ok: false``, and the §15
  close-taxonomy (a ``CorruptPayload`` reply must be retryable, a
  ``VersionMismatch`` reply must not be).
* **retry-safety** — per mutating handler op, every path that reaches a
  state-class mutation (the §21 mutation-summary lattice: direct
  ``self.X`` stores closed over same-class calls) must be dominated by
  a ``DedupWindow`` claim check whose duplicate arm exits — otherwise a
  wire retry applies the op twice (the at-most-once invariant that must
  hold per-shard when the center splits K ways).  Ops declared
  idempotent-by-algebra in the endpoint spec are exempt.
* **state-machine** — the membership machine's exhaustiveness: every
  controller status write emits exactly its declared MEMBERSHIP event,
  every emitted event/hook is in the declared vocabulary (and every
  vocabulary entry is actually emitted), every Reactor subclass handles
  or explicitly ignores every hook, every fleetmon RULE_ACTION is
  dispatched by a declared handler, and wire-header reads stay inside
  the versioned field vocabulary (v2-OPTIONAL fields only via ``.get``).

All three skip what they cannot see: on a partial tree (precommit
staged blobs) a direction that needs cross-file visibility is skipped,
never guessed — ``EndpointSpec.requires`` lists the prerequisites.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import protocol as P
from ..core import Checker, Finding, register
from ..engine import FuncRecord, ProgramIndex


# ---------------------------------------------------------------------------
# wire-contract
# ---------------------------------------------------------------------------

@register
class WireContractChecker(Checker):
    name = "wire-contract"
    description = ("client sent-op tables must match server dispatch "
                   "tables per endpoint; reply verdicts must match the "
                   "wire client's retry policy (§15 close-taxonomy)")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        findings: List[Finding] = []
        present = {s.name: s for s in P.ENDPOINTS
                   if s.server_path in index.by_path}
        tables: Dict[str, Dict[str, P.OpSite]] = {}
        for name, spec in sorted(present.items()):
            t = P.server_op_table(index, spec)
            if t is None:
                findings.append(Finding(
                    self.name, spec.server_path, 1, 0,
                    f"endpoint '{name}': declared dispatch function "
                    f"`{spec.dispatch}` not found — the protocol model "
                    f"(analysis/protocol.py) is out of date"))
                continue
            tables[name] = t

        # the statusz-compatible family: ONE dialer speaks to all of
        # them, so its sent ops pool and diff against the family union
        statusz_specs = [s for s in P.ENDPOINTS if s.statusz_compat]
        family_ready = all(s.name in tables for s in statusz_specs)
        pool = P.statusz_query_ops(index) if family_ready else {}
        if family_ready and statusz_specs:
            family_ops: Set[str] = set()
            for s in statusz_specs:
                family_ops |= set(tables[s.name])
            for op, sites in sorted(pool.items()):
                if op not in family_ops:
                    findings.append(Finding(
                        self.name, sites[0].path, sites[0].line,
                        sites[0].col,
                        f"statusz_query sends op '{op}' that no "
                        f"statusz-compatible endpoint "
                        f"({', '.join(s.name for s in statusz_specs)}) "
                        f"handles"))

        for name, spec in sorted(present.items()):
            if name not in tables:
                continue
            table = tables[name]
            client = P.client_op_table(index, spec)
            for op, sites in sorted(client.items()):
                if op not in table:
                    findings.append(Finding(
                        self.name, sites[0].path, sites[0].line,
                        sites[0].col,
                        f"client sends op '{op}' that endpoint "
                        f"'{name}' has no handler arm for"))
            # the unsent-handler direction needs the full client
            # visibility the spec declares
            if not all(p in index.by_path for p in spec.requires):
                continue
            if spec.statusz_compat and not family_ready:
                continue
            if not spec.clients and not spec.statusz_compat:
                continue
            sent = set(client)
            if spec.statusz_compat:
                sent |= set(pool)
            for op in sorted(table):
                if op not in sent and op not in spec.external_ops:
                    site = table[op]
                    findings.append(Finding(
                        self.name, site.path, site.line, site.col,
                        f"endpoint '{name}' handles op '{op}' that no "
                        f"in-repo client ever sends (declare it in "
                        f"external_ops if it is a query surface, or "
                        f"delete the dead arm)"))

        findings.extend(self._verdict_findings(index, present, tables))
        findings.extend(self._read_findings(index, present, tables))
        return findings

    # -- reply verdicts vs the shared wire client ---------------------------

    def _verdict_findings(self, index, present, tables):
        findings: List[Finding] = []
        wire_specs = [s for s in P.ENDPOINTS if s.wire_verdicts]
        wire_ready = P.WIRE_PATH in index.by_path and \
            all(s.name in tables for s in wire_specs)
        policy = set(P.POLICY_KEYS)
        union_emitted: Set[str] = set()
        wc_reads = set(P.reply_reads(index, P.WIRE_CLIENT_READS)) \
            if wire_ready else set()
        for spec in wire_specs:
            if spec.name not in tables:
                continue
            sites, extra = P.reply_sites(index, spec)
            emitted = set(extra)
            for site in sites:
                if site.keys is not None:
                    emitted |= site.keys
                # a retryable verdict on a successful reply is
                # incoherent: the client only consults `retry` on
                # ok=false replies
                if site.consts.get("retry") is True and \
                        site.consts.get("ok") is not False:
                    findings.append(Finding(
                        self.name, site.path, site.line, 0,
                        f"endpoint '{spec.name}': reply marks "
                        f"retry=true without ok=false — the wire "
                        f"client never consults retry on a success"))
            union_emitted |= emitted
            if wire_ready:
                for k in sorted((emitted & policy) - wc_reads):
                    anchor = next((s for s in sites
                                   if s.keys and k in s.keys), None)
                    findings.append(Finding(
                        self.name, spec.server_path,
                        anchor.line if anchor else 1, 0,
                        f"endpoint '{spec.name}' emits reply verdict "
                        f"'{k}' the wire client never inspects — a "
                        f"dead signal (retryability drift)"))
            # §15 close-taxonomy: exception handlers' replies
            for exc, verdict in sorted(P.EXCEPTION_VERDICTS.items()):
                for site in P.exception_reply_sites(index, spec, exc):
                    has_retry = site.consts.get("retry") is True
                    if verdict == "retryable" and not has_retry:
                        findings.append(Finding(
                            self.name, site.path, site.line, 0,
                            f"endpoint '{spec.name}': the {exc} reply "
                            f"must carry retry=true — a corrupt frame "
                            f"left the stream aligned, the client may "
                            f"retry the same token"))
                    elif verdict == "terminal" and has_retry:
                        findings.append(Finding(
                            self.name, site.path, site.line, 0,
                            f"endpoint '{spec.name}': the {exc} reply "
                            f"must NOT be retryable — a version "
                            f"mismatch is terminal by contract"))
        if wire_ready:
            for k in sorted((wc_reads & policy) - union_emitted):
                findings.append(Finding(
                    self.name, P.WIRE_PATH, 1, 0,
                    f"the wire client inspects reply verdict '{k}' "
                    f"that no handler path of any wire endpoint sets"))
        return findings

    # -- client reads vs literal reply fields -------------------------------

    def _read_findings(self, index, present, tables):
        findings: List[Finding] = []
        for name, spec in sorted(present.items()):
            if name not in tables or not spec.reads:
                continue
            sites, extra = P.reply_sites(index, spec)
            if any(s.keys is None for s in sites):
                continue        # a dynamic reply can set anything
            emitted = set(extra)
            for s in sites:
                emitted |= s.keys
            reads: Dict[str, P.OpSite] = {}
            for surf in spec.reads:
                for k, site in P.reply_reads(index, surf).items():
                    reads.setdefault(k, site)
            for k, site in sorted(reads.items()):
                if k not in emitted and k not in P.REPLY_VERDICT_KEYS:
                    findings.append(Finding(
                        self.name, site.path, site.line, site.col,
                        f"client reads reply field '{k}' that no "
                        f"handler path of endpoint '{name}' sets"))
        return findings


# ---------------------------------------------------------------------------
# retry-safety
# ---------------------------------------------------------------------------

@register
class RetrySafetyChecker(Checker):
    name = "retry-safety"
    description = ("every mutating handler path must be dominated by a "
                   "DedupWindow claim check — at-most-once application "
                   "under wire retries (§15)")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        findings: List[Finding] = []
        for spec in P.ENDPOINTS:
            if not spec.state_attrs or \
                    spec.server_path not in index.by_path:
                continue
            rec = P.dispatch_record(index, spec)
            if rec is None:
                continue              # wire-contract reports the drift
            table = P.server_op_table(index, spec) or {}
            mut = P.mutating_methods(index, spec.state_classes)
            aliases = P.state_aliases(index, spec, spec.state_attrs)
            dedup_aliases = P.state_aliases(index, spec,
                                            spec.dedup_attrs)
            selves = P.self_aliases(index, spec)
            opvars = P.op_var_names(rec.node)
            for op in sorted(table):
                if op in spec.idempotent_ops:
                    continue
                walker = _ClaimWalker(self.name, index, spec, rec,
                                      opvars, op, aliases,
                                      dedup_aliases, selves, mut,
                                      findings)
                walker.walk(list(rec.node.body), claimed=False)
        return findings


class _ClaimWalker:
    """Walk one op's handler slice of a dispatch function, tracking
    whether execution is past a DedupWindow claim whose duplicate arm
    exits.  Dispatch ``if`` tests that are pure functions of the op
    variable are folded to the slice for this op; everything else is
    walked both ways."""

    def __init__(self, check, index, spec, rec, opvars, op, aliases,
                 dedup_aliases, selves, mut, findings):
        self.check = check
        self.index = index
        self.spec = spec
        self.rec = rec
        self.opvars = opvars
        self.op = op
        self.aliases = aliases
        self.dedup_aliases = dedup_aliases
        self.selves = selves
        self.mut = mut
        self.findings = findings
        self.claim_vars: Set[str] = set()
        self._reported: Set[Tuple[int, str]] = set()

    # -- statement walk -----------------------------------------------------

    def walk(self, stmts: Sequence[ast.stmt], claimed: bool) -> bool:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue              # runs when called, not here
            if isinstance(st, ast.If):
                claimed = self._walk_if(st, claimed)
                continue
            if isinstance(st, ast.Try):
                claimed_body = self.walk(st.body, claimed)
                for h in st.handlers:
                    # a handler may run from any point in the body —
                    # only claims made BEFORE the try are certain
                    self.walk(h.body, claimed)
                claimed_body = self.walk(st.orelse, claimed_body)
                claimed_body = self.walk(st.finalbody, claimed_body)
                claimed = claimed_body
                continue
            if isinstance(st, (ast.For, ast.While, ast.With)):
                for expr in self._stmt_exprs(st):
                    self._scan_expr(expr, claimed)
                claimed = self.walk(st.body, claimed)
                claimed = self.walk(getattr(st, "orelse", []), claimed)
                continue
            if self._claim_assign(st):
                continue              # the claim call itself
            # the statement NODE itself is part of the scan: a direct
            # `center.x += 1` is the Assign/AugAssign at statement level
            self._scan_expr(st, claimed)
        return claimed

    def _walk_if(self, st: ast.If, claimed: bool) -> bool:
        fold = P.fold_op_test(st.test, self.opvars, self.op,
                              self.rec.sf, self.index)
        if fold is True:
            self._scan_expr(st.test, claimed)
            return self.walk(st.body, claimed)
        if fold is False:
            self._scan_expr(st.test, claimed)
            return self.walk(st.orelse, claimed)
        # the duplicate gate: `if dup:` after a claim assignment whose
        # body exits — everything after runs exactly-once
        if isinstance(st.test, ast.Name) and \
                st.test.id in self.claim_vars:
            self.walk(st.body, True)      # the dedup/replay path
            self.walk(st.orelse, claimed)
            if P.block_terminates(st.body):
                return True
            return claimed
        self._scan_expr(st.test, claimed)
        cb = self.walk(st.body, claimed)
        co = self.walk(st.orelse, claimed)
        body_exits = P.block_terminates(st.body)
        orelse_exits = P.block_terminates(st.orelse)
        # after the if: claimed on every surviving path
        return claimed or ((cb or body_exits) and (co or orelse_exits))

    @staticmethod
    def _stmt_exprs(st: ast.stmt):
        if isinstance(st, ast.For):
            return [st.iter]
        if isinstance(st, ast.While):
            return [st.test]
        if isinstance(st, ast.With):
            return [i.context_expr for i in st.items]
        return []

    # -- claims -------------------------------------------------------------

    def _claim_assign(self, st: ast.stmt) -> bool:
        """``dup, cached = <dedup>.check(...)`` — record the claim
        variable."""
        if not isinstance(st, ast.Assign) or \
                not isinstance(st.value, ast.Call):
            return False
        root, chain = P._attr_root(st.value.func)
        is_claim = (root in self.dedup_aliases and chain == ["check"]) \
            or (root in self.selves and len(chain) == 2 and
                chain[0] in self.spec.dedup_attrs and
                chain[1] == "check")
        if not is_claim:
            return False
        t = st.targets[0]
        if isinstance(t, ast.Tuple) and t.elts and \
                isinstance(t.elts[0], ast.Name):
            self.claim_vars.add(t.elts[0].id)
        elif isinstance(t, ast.Name):
            self.claim_vars.add(t.id)
        return True

    # -- mutation scan ------------------------------------------------------

    def _state_chain(self, node: ast.AST):
        """(display root, attr chain BELOW the state object) when the
        expression is rooted at the server-owned state — through a local
        alias (``center.x``) or directly through ``self``/any derived
        self-capture (``self.center.x``, ``outer.center.x``)."""
        root, chain = P._attr_root(node)
        if root in self.aliases and chain:
            return root, chain
        if root in self.selves and len(chain) >= 2 and \
                chain[0] in self.spec.state_attrs:
            return f"{root}.{chain[0]}", chain[1:]
        return None, []

    def _scan_expr(self, expr: ast.AST, claimed: bool) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            hit = None
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign, ast.Delete)):
                targets = node.targets if isinstance(
                    node, (ast.Assign, ast.Delete)) else [node.target]
                for t in targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    root, chain = self._state_chain(base)
                    if root is not None:
                        hit = f"writes `{root}.{'.'.join(chain)}`"
            elif isinstance(node, ast.Attribute):
                root, chain = self._state_chain(node)
                if root is not None:
                    if chain[-1] in self.mut:
                        hit = f"calls mutating `{root}." \
                              f"{'.'.join(chain)}`"
                    elif len(chain) >= 2 and \
                            chain[-1] in P.CONTAINER_MUTATORS:
                        hit = f"mutates container `{root}." \
                              f"{'.'.join(chain[:-1])}`"
            if hit is None or claimed:
                continue
            line = getattr(node, "lineno", 1)
            key = (line, hit)
            if key in self._reported:
                continue
            self._reported.add(key)
            self.findings.append(Finding(
                self.check, self.rec.sf.path, line,
                getattr(node, "col_offset", 0),
                f"endpoint '{self.spec.name}' op '{self.op}': handler "
                f"path {hit} without a dominating DedupWindow claim "
                f"check — a wire retry applies this op twice "
                f"(at-most-once violation; declare the op in "
                f"idempotent_ops only if the mutation is idempotent "
                f"by algebra)"))


# ---------------------------------------------------------------------------
# state-machine
# ---------------------------------------------------------------------------

@register
class StateMachineChecker(Checker):
    name = "state-machine"
    description = ("membership transitions must emit exactly their "
                   "declared events, reactors must handle or ignore "
                   "every hook, alert actions must be dispatched, and "
                   "wire-header reads must stay in the versioned "
                   "vocabulary")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        findings: List[Finding] = []
        self._controller_findings(index, findings)
        self._reactor_findings(index, findings)
        self._action_findings(index, findings)
        self._header_findings(index, findings)
        return findings

    # -- controller transitions ---------------------------------------------

    def _controller_findings(self, index, findings):
        module, cls = P.CONTROLLER_CLASS
        recs = [r for r in index.records.values()
                if r.class_key == (module, cls)]
        if not recs:
            return
        path = recs[0].sf.path
        vocab = index.module_constant(P.MEMBERSHIP_VOCAB)
        center_vocab = index.module_constant(P.CENTER_VOCAB)
        vocab = vocab if isinstance(vocab, tuple) else None
        center_vocab = center_vocab if isinstance(center_vocab, tuple) \
            else ()
        if vocab is None:
            findings.append(Finding(
                self.name, path, 1, 0,
                "MEMBERSHIP_EVENTS vocabulary tuple not found next to "
                "MembershipController — the transition contract has no "
                "declared event set"))
            return
        all_emits: Set[str] = set()
        for rec in sorted(recs, key=lambda r: r.node.lineno):
            emits = self._emit_literals(rec)
            events = self._event_literals(rec)
            all_emits |= emits
            for status, node in self._status_writes(rec):
                expected = P.STATUS_EVENTS.get(status)
                if expected is None:
                    findings.append(Finding(
                        self.name, path, node.lineno, node.col_offset,
                        f"`{rec.name}` writes undeclared worker status "
                        f"{status!r} — the declared machine knows "
                        f"{sorted(P.STATUS_EVENTS)}"))
                elif expected not in emits:
                    findings.append(Finding(
                        self.name, path, node.lineno, node.col_offset,
                        f"`{rec.name}` transitions a worker to "
                        f"{status!r} without emitting its declared "
                        f"'{expected}' event — the reactors and the "
                        f"chaos audit never see this transition"))
            for ev, hook, node in self._emit_calls(rec):
                if ev is not None and ev not in vocab:
                    findings.append(Finding(
                        self.name, path, node.lineno, node.col_offset,
                        f"`{rec.name}` emits event {ev!r} outside the "
                        f"declared MEMBERSHIP_EVENTS vocabulary "
                        f"{sorted(vocab)}"))
                if hook is not None and hook not in P.REACTOR_HOOKS:
                    findings.append(Finding(
                        self.name, path, node.lineno, node.col_offset,
                        f"`{rec.name}` fans out through undeclared "
                        f"reactor hook {hook!r}"))
                elif ev in P.EVENT_HOOKS and hook is not None and \
                        hook not in P.EVENT_HOOKS[ev]:
                    findings.append(Finding(
                        self.name, path, node.lineno, node.col_offset,
                        f"event {ev!r} fans out through hook {hook!r} "
                        f"— declared hooks are "
                        f"{list(P.EVENT_HOOKS[ev])}"))
            for ev in events:
                if ev not in vocab and ev not in center_vocab:
                    findings.append(Finding(
                        self.name, path, rec.node.lineno, 0,
                        f"`{rec.name}` streams telemetry event {ev!r} "
                        f"outside the declared membership/center "
                        f"vocabularies"))
        for ev in vocab:
            if ev not in all_emits:
                findings.append(Finding(
                    self.name, path, 1, 0,
                    f"declared MEMBERSHIP_EVENTS entry {ev!r} is never "
                    f"emitted by any MembershipController transition — "
                    f"dead vocabulary or a dropped emit"))

    @staticmethod
    def _status_writes(rec: FuncRecord):
        out = []
        for sub in ast.walk(rec.node):
            values: List[ast.AST] = []
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.slice, ast.Constant) and \
                            t.slice.value == "status":
                        values.append(sub.value)
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "update":
                for kw in sub.keywords:
                    if kw.arg == "status":
                        values.append(kw.value)
            for v in values:
                if isinstance(v, ast.IfExp):
                    for arm in (v.body, v.orelse):
                        if isinstance(arm, ast.Constant) and \
                                isinstance(arm.value, str):
                            out.append((arm.value, arm))
                elif isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    out.append((v.value, v))
        return out

    @staticmethod
    def _emit_calls(rec: FuncRecord):
        """(event literal, hook literal, node) per ``self._emit`` call."""
        out = []
        for sub in ast.walk(rec.node):
            if not isinstance(sub, ast.Call) or \
                    not isinstance(sub.func, ast.Attribute) or \
                    sub.func.attr != "_emit":
                continue
            ev = hook = None
            if sub.args and isinstance(sub.args[0], ast.Constant):
                ev = sub.args[0].value
            if len(sub.args) > 2 and isinstance(sub.args[2],
                                                ast.Constant):
                hook = sub.args[2].value
            out.append((ev, hook, sub))
        return out

    def _emit_literals(self, rec: FuncRecord) -> Set[str]:
        return {ev for ev, _, _ in self._emit_calls(rec)
                if isinstance(ev, str)}

    @staticmethod
    def _event_literals(rec: FuncRecord) -> Set[str]:
        """Literal ``<tm>.event("...")`` names — the transitions that
        stream without the ``_emit`` fan-out (the center pair)."""
        out: Set[str] = set()
        for sub in ast.walk(rec.node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "event" and sub.args and \
                    isinstance(sub.args[0], ast.Constant) and \
                    isinstance(sub.args[0].value, str):
                out.add(sub.args[0].value)
        return out

    # -- reactor exhaustiveness ---------------------------------------------

    def _reactor_findings(self, index, findings):
        root_key = index._class_keys.get(P.REACTOR_ROOT)
        if root_key is None:
            return
        for key in index.subclasses_of(P.REACTOR_ROOT):
            if key == root_key:
                continue
            module, cls = key
            sf = next((f for f in index.files
                       if f.resolver.module == module), None)
            if sf is None or sf.path.startswith("tests/"):
                continue
            node = index.file_index[sf.path].classes.get(cls)
            line = node.lineno if node is not None else 1
            for hook in P.REACTOR_HOOKS:
                if f"{module}.{cls}.{hook}" not in index.by_qualname:
                    findings.append(Finding(
                        self.name, sf.path, line, 0,
                        f"reactor `{cls}` neither handles nor "
                        f"explicitly ignores `{hook}` — every reactor "
                        f"must decide every event in the vocabulary "
                        f"(override with `pass` to ignore)"))

    # -- alert-action dispatch ----------------------------------------------

    def _action_findings(self, index, findings):
        actions = index.module_constant(P.ACTIONS_VOCAB)
        if not isinstance(actions, tuple):
            return
        handler_recs: List[FuncRecord] = []
        for path, suffix in P.ACTION_HANDLERS:
            qn = f"{P.module_of(path)}.{suffix}"
            handler_recs.extend(r for r in index.by_qualname.get(qn, [])
                                if r.sf.path == path)
        if not handler_recs:
            return
        for action in actions:
            handled = False
            for rec in handler_recs:
                for sub in ast.walk(rec.node):
                    if isinstance(sub, ast.Compare) and any(
                            isinstance(c, ast.Constant) and
                            c.value == action
                            for c in sub.comparators):
                        handled = True
            if not handled:
                anchor = handler_recs[0]
                findings.append(Finding(
                    self.name, anchor.sf.path, anchor.node.lineno, 0,
                    f"declared alert action {action!r} "
                    f"(fleetmon.RULE_ACTIONS) is dispatched by no "
                    f"declared handler "
                    f"({', '.join(s for _, s in P.ACTION_HANDLERS)}) — "
                    f"an alert carrying it would be silently dropped"))

    # -- wire-header field vocabulary ----------------------------------------

    def _header_findings(self, index, findings):
        for spec in P.ENDPOINTS:
            if spec.server_path not in index.by_path:
                continue
            for read in P.header_reads(index, spec):
                decl = P.HEADER_FIELDS.get(read.fieldname)
                if decl is None:
                    findings.append(Finding(
                        self.name, read.path, read.line, 0,
                        f"endpoint '{spec.name}' reads undeclared "
                        f"wire-header field '{read.fieldname}' — "
                        f"declare it in protocol.HEADER_FIELDS with "
                        f"the protocol version that introduces it "
                        f"(the v1→v2 `trace` precedent)"))
                elif read.subscript and not decl[1]:
                    findings.append(Finding(
                        self.name, read.path, read.line, 0,
                        f"endpoint '{spec.name}' subscript-reads "
                        f"v{decl[0]}-optional header field "
                        f"'{read.fieldname}' — a v1 peer omits it; "
                        f"read it with .get() (version guard)"))
