"""The project-specific checker suite — importing this package registers
every checker with :data:`~..core.CHECKERS` (docs/design.md §12)."""

from . import (  # noqa: F401
    compat_boundary,
    donation_safety,
    rng_discipline,
    schema_drift,
    telemetry_hot_path,
    trace_purity,
)
