"""The project-specific checker suite — importing this package registers
every checker with :data:`~..core.CHECKERS` (docs/design.md §12).

The dataflow checkers (trace-purity, rng-discipline, donation-safety,
collective-discipline, sharding-schema, exchange-symmetry) run on the
whole-program engine (``analysis/engine.py``); the host-concurrency pass
(shared-state-race, lock-ordering, signal-safety, daemon-discipline)
runs on the engine's thread-role inference; the protocol pass
(wire-contract, retry-safety, state-machine) runs on the declared
endpoint model (``analysis/protocol.py``, docs/design.md §21);
compat-boundary and telemetry-hot-path stay per-file (their invariants
are lexical); schema-drift is the live-object project probe, and
oracle-pair is the disk-scoped project probe pinning every ops/ Pallas
kernel to a registered jnp oracle with an equality test.  The
compile-surface pass (cache-key, retrace-hazard, dtype-flow) guards the
AOT executable-cache contract: key_extra completeness, silent-recompile
call shapes, and low-precision wire numerics (docs/design.md §26).
"""

from . import (  # noqa: F401
    collective_discipline,
    compat_boundary,
    compile_surface,
    donation_safety,
    exchange_symmetry,
    host_concurrency,
    oracle_pair,
    protocol_conformance,
    rng_discipline,
    schema_drift,
    sharding_schema,
    telemetry_hot_path,
    trace_purity,
)

#: ``--only``/``--disable`` group aliases: ``--only concurrency`` runs
#: just the host-concurrency pass, ``--only protocol`` the distributed-
#: protocol conformance pass (scripts/lint.py expands these before
#: checker-name validation, so the cache keys on the real names).
CHECK_GROUPS = {
    "compile-surface": ("cache-key", "dtype-flow", "retrace-hazard"),
    "concurrency": ("daemon-discipline", "lock-ordering",
                    "shared-state-race", "signal-safety"),
    "protocol": ("wire-contract", "retry-safety", "state-machine"),
}
