"""collective-discipline: named-axis collectives must be well-formed SPMD.

Three invariants (docs/design.md §12), guarding the ROADMAP-item-1
bucketed overlap-scheduled collectives before they exist:

1. **Axis names are real.**  Every ``lax.psum`` / ``pmean`` /
   ``ppermute`` / ``all_gather`` / ``all_to_all`` / ``axis_index`` /
   ``psum_scatter`` (and ``jax_compat`` shim) call whose axis argument
   is statically evaluable must name an axis the program can actually
   bind: the axes ``parallel/mesh.py`` declares (``*_AXIS`` module
   constants — the one source of truth, read live from the parsed file)
   plus any axis literally declared in the SAME file (``Mesh(devs,
   ("workers", "seq"))``, ``axis_name="seq"``).  A typo'd axis traces
   fine and deadlocks (or mis-reduces) at run time on the pod — the
   static check catches it in seconds.  Unknown (parameter-passed,
   computed) axis arguments are SKIPPED, never guessed.

2. **No collectives under rank-divergent branches.**  A collective
   lexically inside a Python ``if``/``while``/conditional-expression
   whose test dataflows from ``lax.axis_index`` / ``jax.process_index``
   is a divergence hazard: under multi-host SPMD each process traces
   its own program, so a rank-dependent Python branch makes some hosts
   issue a collective others never reach — the canonical SPMD deadlock.
   The same applies to a ``lax.cond``/``lax.switch`` whose predicate is
   rank-derived when a branch (transitively) issues collectives.
   Dataflow is per-function: names assigned from the two APIs taint,
   taint propagates through assignments.

3. **Paired start/done APIs match — the bucket-balance probe.**  Async
   collective pairs (``lax.<x>_start`` / ``lax.<x>_done`` and the
   ``jax_compat`` shims the bucketed overlap wire of
   ``parallel/buckets.py`` issues) must balance within one function
   scope: a start with no done leaks an in-flight collective, a done
   with no start is undefined.  Additionally a ``<x>_start`` whose
   ticket is DISCARDED (a bare expression statement) is flagged even
   when another start/done pair balances the counts — the in-flight
   token must reach its done.  The shim-definition module itself
   (``theanompi_tpu/jax_compat.py``) is exempt: each shim half
   lexically contains its one-sided underlying ``lax`` call by
   construction — that file IS the pairing boundary.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, SourceFile, register
from ..engine import (COLLECTIVES, FuncRecord, ProgramIndex, axis_values,
                      body_walk, collective_name)

MESH_MODULE = "theanompi_tpu.parallel.mesh"

# fallback when parallel/mesh.py is not in the linted file set (single
# -file fixture runs) — mirrors its *_AXIS declarations
DEFAULT_DECLARED = ("workers", "model", "pipe", "seq")

RANK_SOURCES = {
    "jax.lax.axis_index", "jax.process_index",
    "theanompi_tpu.jax_compat.axis_index",
}

_ASYNC_MODULES = ("jax.lax.", "theanompi_tpu.jax_compat.")

# the module DEFINING the async shims: each `<x>_start`/`<x>_done` half
# wraps its one-sided underlying lax call, so pairing is structurally
# one-sided there by construction — exempt from the balance probe
_SHIM_MODULE = "theanompi_tpu.jax_compat"


def _async_pair(resolved: Optional[str]) -> Optional[Tuple[str, str]]:
    """('prefix', 'start'|'done') of an async collective API name."""
    if not resolved:
        return None
    for mod in _ASYNC_MODULES:
        if resolved.startswith(mod):
            simple = resolved[len(mod):]
            for suffix in ("start", "done"):
                if simple.endswith("_" + suffix):
                    return simple[:-(len(suffix) + 1)], suffix
    return None


@register
class CollectiveDisciplineChecker(Checker):
    name = "collective-discipline"
    description = ("collective axis names must be declared mesh axes; no "
                   "collectives under rank-derived branches; start/done "
                   "pairs must balance")
    needs_engine = True

    def check_program(self, index: ProgramIndex):
        declared = self._declared_axes(index)
        self._index_consts = index._module_constants
        findings: List[Finding] = []
        for sf in index.files:
            valid = declared | self._file_axes(sf)
            module_consts = {
                name.rsplit(".", 1)[-1]: v
                for name, v in index._module_constants.items()
                if name.startswith(sf.resolver.module + ".")
                and isinstance(v, str)}
            # module scope + every function scope
            scopes: List[Optional[ast.AST]] = [None]
            scopes += [n for n in ast.walk(sf.tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            for scope in scopes:
                self._check_scope(index, sf, scope, valid, module_consts,
                                  findings)
        return findings

    # -- axis vocabulary ---------------------------------------------------

    def _declared_axes(self, index: ProgramIndex) -> Set[str]:
        axes = {v for name, v in index._module_constants.items()
                if name.startswith(MESH_MODULE + ".")
                and name.rsplit(".", 1)[-1].endswith("_AXIS")
                and isinstance(v, str)}
        return axes or set(DEFAULT_DECLARED)

    def _file_axes(self, sf: SourceFile) -> Set[str]:
        """Axes literally declared in this file: ``Mesh(devs, (...))``
        axis tuples and ``axis_name=``/``axis_names=`` kwarg literals."""
        out: Set[str] = set()
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = sf.resolver.resolve(node.func)
            is_mesh = (resolved or "").endswith("sharding.Mesh") or \
                (isinstance(node.func, ast.Name) and
                 node.func.id == "Mesh") or \
                (isinstance(node.func, ast.Attribute) and
                 node.func.attr == "Mesh")
            if is_mesh and len(node.args) > 1:
                out.update(self._str_literals(node.args[1]))
            # `axis_name=` on a BINDER (Mesh/worker_mesh/pmap/...)
            # declares an axis; on a COLLECTIVE it is the argument under
            # validation — harvesting it there would self-whitelist the
            # very typo this checker exists to catch
            if collective_name(resolved) is not None:
                continue
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    out.update(self._str_literals(kw.value))
        return out

    @staticmethod
    def _str_literals(node: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and \
                    isinstance(sub.value, str):
                out.add(sub.value)
        return out

    # -- per-scope checks --------------------------------------------------

    def _scope_stmts(self, sf: SourceFile, scope: Optional[ast.AST]):
        """Statements belonging to this scope only (no nested defs)."""
        body = sf.tree.body if scope is None else scope.body
        stack = list(body)
        while stack:
            st = stack.pop()
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            yield st
            for fieldname in ("body", "orelse", "finalbody"):
                stack.extend(getattr(st, fieldname, []) or [])
            for h in getattr(st, "handlers", []):
                stack.extend(h.body)

    def _check_scope(self, index: ProgramIndex, sf: SourceFile, scope,
                     valid: Set[str], module_consts: Dict[str, str],
                     findings: List[Finding]) -> None:
        local_consts = dict(module_consts)
        tainted = self._tainted_names(sf, scope, local_consts)
        stmts = list(self._scope_stmts(sf, scope))
        seen_hazard: Set[Tuple[int, int]] = set()

        # 1 + 3: axis validity and start/done balance (the bucket-balance
        # probe).  Each call is visited exactly once: through the
        # expression roots of its own statement (nested block statements
        # are yielded separately).  The shim-definition module is exempt
        # from pairing — each shim half is one-sided by construction.
        check_pairs = sf.resolver.module != _SHIM_MODULE
        pairs: Dict[str, Dict[str, List[ast.Call]]] = {}
        ticket_assigns: List[Tuple[str, ast.Call, str]] = []
        for st in stmts:
            for expr in self._stmt_exprs(st):
                for call in self._calls(expr):
                    resolved = sf.resolver.resolve(call.func)
                    cname = collective_name(resolved)
                    if cname is not None:
                        for axis in axis_values(call, cname, sf.resolver,
                                                index, local_consts):
                            if isinstance(axis, str) and axis not in valid:
                                findings.append(Finding(
                                    self.name, sf.path, call.lineno,
                                    call.col_offset,
                                    f"collective `{cname}` over "
                                    f"undeclared mesh axis '{axis}' "
                                    "(declared: "
                                    f"{', '.join(sorted(valid))})"))
                    ap = _async_pair(resolved)
                    if ap is not None and check_pairs:
                        pairs.setdefault(ap[0], {}).setdefault(
                            ap[1], []).append(call)
                        if ap[1] == "start" and \
                                isinstance(st, ast.Assign) and \
                                len(st.targets) == 1 and \
                                isinstance(st.targets[0], ast.Name) and \
                                st.value is call:
                            # candidate for the dead-ticket probe below
                            ticket_assigns.append(
                                (st.targets[0].id, call, ap[0]))
                        if ap[1] == "start" and isinstance(st, ast.Expr) \
                                and st.value is call:
                            # ticket discarded on the floor: even with the
                            # counts balanced elsewhere, THIS in-flight
                            # collective can never be awaited
                            findings.append(Finding(
                                self.name, sf.path, call.lineno,
                                call.col_offset,
                                f"leaked in-flight collective: "
                                f"`{ap[0]}_start` ticket is discarded "
                                f"(bare expression statement) — it can "
                                f"never reach `{ap[0]}_done`"))
        unbalanced: Set[str] = set()
        for prefix, sides in sorted(pairs.items()):
            starts = sides.get("start", [])
            dones = sides.get("done", [])
            if len(starts) != len(dones):
                unbalanced.add(prefix)
                anchor = (starts or dones)[0]
                findings.append(Finding(
                    self.name, sf.path, anchor.lineno, anchor.col_offset,
                    f"unbalanced async collective pair: "
                    f"{len(starts)}x `{prefix}_start` vs {len(dones)}x "
                    f"`{prefix}_done` in the same scope"))

        # 3b: dead-ticket probe (round 10, the per-schedule-slot hop of
        # the interleaved pipeline scan body): a name assigned from a
        # `<x>_start` and never read again cannot reach its done even
        # when the scope's start/done COUNTS balance through other pairs
        # (e.g. a typo'd done consuming the wrong ticket twice) — that
        # schedule slot's hop is leaked in-flight every tick.  Loads are
        # collected over the whole scope subtree (nested defs included)
        # so a ticket consumed by a closure never false-positives; an
        # unbalanced prefix is already reported above, so the probe only
        # speaks when the counts LOOK healthy.
        if check_pairs and ticket_assigns:
            loaded: Set[str] = set()
            for sub in ast.walk(scope if scope is not None else sf.tree):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load):
                    loaded.add(sub.id)
            for tname, call, prefix in ticket_assigns:
                if tname not in loaded and prefix not in unbalanced:
                    findings.append(Finding(
                        self.name, sf.path, call.lineno, call.col_offset,
                        f"dropped hop ticket: `{tname}` holds the "
                        f"`{prefix}_start` in-flight collective but is "
                        f"never consumed — this slot's hop can never "
                        f"reach `{prefix}_done`"))

        # 2: collectives under rank-derived branches
        for st in stmts:
            if isinstance(st, (ast.If, ast.While)) and \
                    self._test_tainted(sf, st.test, tainted):
                for arm in (st.body, st.orelse):
                    self._flag_collectives_under(
                        index, sf, scope, arm, st, seen_hazard, findings)
            for expr in self._stmt_exprs(st):
                for node in ast.walk(expr):
                    if isinstance(node, ast.IfExp) and \
                            self._test_tainted(sf, node.test, tainted):
                        self._flag_collectives_under(
                            index, sf, scope, [node.body, node.orelse],
                            node, seen_hazard, findings)
                    elif isinstance(node, ast.Call):
                        resolved = sf.resolver.resolve(node.func)
                        if resolved in ("jax.lax.cond",
                                        "jax.lax.switch") \
                                and node.args and self._test_tainted(
                                    sf, node.args[0], tainted):
                            self._flag_cond_branches(index, sf, scope,
                                                     node, findings)

    @staticmethod
    def _calls(node: ast.AST):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                yield sub

    @staticmethod
    def _exec_calls(node: ast.AST):
        """Calls executed when this subtree runs: descends lambdas
        (tree.map bodies run here) but not nested function DEFINITIONS
        (merely defining one issues nothing)."""
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(sub, ast.Call):
                yield sub
            stack.extend(ast.iter_child_nodes(sub))

    @staticmethod
    def _stmt_exprs(st: ast.stmt):
        """Expression roots of one statement — its non-statement AST
        children (nested statement blocks are separate scope items)."""
        for _, value in ast.iter_fields(st):
            if isinstance(value, ast.AST) and not isinstance(value,
                                                             ast.stmt):
                yield value
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.AST) and \
                            not isinstance(v, (ast.stmt,
                                               ast.excepthandler)):
                        yield v

    def _tainted_names(self, sf: SourceFile, scope,
                       local_consts: Dict[str, str]) -> Set[str]:
        """Names whose value dataflows from axis_index/process_index —
        and, on the way, fold string-literal assignments into
        ``local_consts`` (the axis-name constant propagation)."""
        tainted: Set[str] = set()
        stmts = list(self._scope_stmts(sf, scope))

        def expr_tainted(expr: ast.AST) -> bool:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Call) and \
                        sf.resolver.resolve(sub.func) in RANK_SOURCES:
                    return True
                if isinstance(sub, ast.Name) and sub.id in tainted and \
                        isinstance(sub.ctx, ast.Load):
                    return True
            return False

        def fold_consts(target: ast.AST, value: ast.AST) -> None:
            if isinstance(target, ast.Name):
                if isinstance(value, ast.Constant) and \
                        isinstance(value.value, str):
                    local_consts[target.id] = value.value
                elif isinstance(value, (ast.Name, ast.Attribute)):
                    resolved = sf.resolver.resolve(value)
                    if resolved:
                        # imported mesh-axis constant
                        v = self._index_consts.get(resolved)
                        if isinstance(v, str):
                            local_consts[target.id] = v
                    elif isinstance(value, ast.Name) and \
                            value.id in local_consts:
                        local_consts[target.id] = local_consts[value.id]
            elif isinstance(target, (ast.Tuple, ast.List)) and \
                    isinstance(value, (ast.Tuple, ast.List)) and \
                    len(target.elts) == len(value.elts):
                for t, v in zip(target.elts, value.elts):
                    fold_consts(t, v)

        changed = True
        passes = 0
        while changed and passes < 10:
            changed = False
            passes += 1
            for st in stmts:
                targets, value = [], None
                if isinstance(st, ast.Assign):
                    targets, value = st.targets, st.value
                elif isinstance(st, ast.AnnAssign) and st.value is not None:
                    targets, value = [st.target], st.value
                elif isinstance(st, ast.AugAssign):
                    targets, value = [st.target], st.value
                if value is None:
                    continue
                for t in targets:
                    fold_consts(t, value)
                if expr_tainted(value):
                    for t in targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name) and \
                                    sub.id not in tainted:
                                tainted.add(sub.id)
                                changed = True
        return tainted

    def _test_tainted(self, sf: SourceFile, test: ast.AST,
                      tainted: Set[str]) -> bool:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Name) and sub.id in tainted and \
                    isinstance(sub.ctx, ast.Load):
                return True
            if isinstance(sub, ast.Call) and \
                    sf.resolver.resolve(sub.func) in RANK_SOURCES:
                return True
        return False

    def _flag_collectives_under(self, index: ProgramIndex, sf: SourceFile,
                                scope, arm, branch_node,
                                seen_hazard: Set[Tuple[int, int]],
                                findings: List[Finding]) -> None:
        nodes = arm if isinstance(arm, list) else [arm]
        for n in nodes:
            for call in self._exec_calls(n):
                if (call.lineno, call.col_offset) in seen_hazard:
                    continue
                resolved = sf.resolver.resolve(call.func)
                cname = collective_name(resolved)
                via = None
                if cname is None:
                    fidx = index.file_index[sf.path]
                    enc = fidx.enclosing.get(id(call.func), scope)
                    for tgt in index.resolve_call(sf, call.func, enc):
                        ts = index.transitive_summary(tgt)
                        if ts.issues_collective:
                            cname = "/".join(sorted(
                                ts.collective_names)) or "collective"
                            via = tgt.name
                            break
                if cname is None:
                    continue
                msg = (f"divergence hazard: collective `{cname}` under a "
                       f"branch whose condition derives from "
                       f"axis_index/process_index (line "
                       f"{branch_node.lineno}) — some ranks may never "
                       "issue it")
                if via:
                    msg = (f"divergence hazard: call to `{via}` (issues "
                           f"`{cname}`) under a branch whose condition "
                           f"derives from axis_index/process_index "
                           f"(line {branch_node.lineno}) — some ranks "
                           "may never issue it")
                seen_hazard.add((call.lineno, call.col_offset))
                findings.append(Finding(self.name, sf.path, call.lineno,
                                        call.col_offset, msg))

    def _flag_cond_branches(self, index: ProgramIndex, sf: SourceFile,
                            scope, cond_call: ast.Call,
                            findings: List[Finding]) -> None:
        fidx = index.file_index[sf.path]
        for arg in cond_call.args[1:]:
            targets: List[FuncRecord] = []
            if isinstance(arg, ast.Lambda):
                rec = index.record_for(arg)
                if rec is not None:
                    targets = [rec]
            elif isinstance(arg, (ast.Name, ast.Attribute)):
                enc = fidx.enclosing.get(id(arg), scope)
                targets = index.resolve_call(sf, arg, enc)
            for tgt in targets:
                ts = index.transitive_summary(tgt)
                if ts.issues_collective:
                    names = "/".join(sorted(ts.collective_names)) or \
                        "collective"
                    findings.append(Finding(
                        self.name, sf.path, cond_call.lineno,
                        cond_call.col_offset,
                        f"divergence hazard: `lax.cond`/`lax.switch` "
                        f"with a rank-derived predicate selects branch "
                        f"`{tgt.name}` issuing `{names}` — predicate "
                        "must be uniform across ranks"))
                    return

    # constants from the engine, stashed per run by check_program's caller
    _index_consts: Dict[str, object] = {}
