"""tpulint framework: findings, per-file AST walk, shared import
resolver, inline suppression, baseline bookkeeping.

A checker is a small class registered via :func:`register`; the runner
(:func:`run_lint`) parses every file in scope ONCE into a
:class:`SourceFile` (source text + AST + :class:`ImportResolver` +
suppression map) and hands it to each applicable checker, so N checkers
cost one parse.  Project-level checkers (schema-drift's live probe)
implement :meth:`Checker.check_project` instead and run once per
invocation.

Baseline contract (``tpulint_baseline.json``): entries match findings by
``(check, path, message)`` — NOT by line, so unrelated edits above a
grandfathered finding don't churn the file — as a multiset (two
identical findings need two entries).  ``--update-baseline`` writes the
file deterministically: entries sorted by (check, path, message), paths
repo-relative POSIX, existing justifications preserved.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Files/dirs the repo-wide walk visits by default (repo-relative).
DEFAULT_PATHS = ("theanompi_tpu", "scripts", "tests", "bench.py")

BASELINE_NAME = "tpulint_baseline.json"

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_\-, ]+)")


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Finding:
    """One checker hit.  ``fingerprint`` (check, path, message) is the
    baseline-matching identity; ``line``/``col`` are for humans."""

    check: str
    path: str          # repo-relative, POSIX separators
    line: int
    col: int
    message: str

    @property
    def fingerprint(self) -> Tuple[str, str, str]:
        return (self.check, self.path, self.message)

    @property
    def stable_id(self) -> str:
        """Line-insensitive hex id for machine consumers (--format json,
        docs/design.md §12): sha1 over ``check|path|message``, 12 hex
        chars — stable across unrelated edits exactly like the baseline
        identity it hashes."""
        import hashlib
        return hashlib.sha1(
            "|".join(self.fingerprint).encode()).hexdigest()[:12]

    def sort_key(self):
        return (self.path, self.line, self.col, self.check, self.message)

    def to_dict(self) -> dict:
        return {"check": self.check, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.check}] " \
               f"{self.message}"


# ---------------------------------------------------------------------------
# import resolver (shared by every AST checker)
# ---------------------------------------------------------------------------

class ImportResolver:
    """Maps names/attribute chains in one module to absolute dotted paths.

    ``import jax.numpy as jnp`` → ``jnp`` = ``jax.numpy``;
    ``from jax import lax`` → ``lax`` = ``jax.lax``;
    ``from ..jax_compat import shard_map`` (in
    ``theanompi_tpu/parallel/steps.py``) → ``shard_map`` =
    ``theanompi_tpu.jax_compat.shard_map``.  :meth:`resolve` then turns a
    ``Name``/``Attribute`` node into its absolute dotted path (``None``
    when the base is not an import — locals, ``self``, call results)."""

    def __init__(self, relpath: str, tree: ast.AST):
        self.module = relpath[:-3].replace("/", ".") \
            if relpath.endswith(".py") else relpath.replace("/", ".")
        # package the module lives in, for relative-import resolution
        self.package = self.module.rpartition(".")[0]
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                base = self.resolve_from_module(node)
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{base}.{a.name}"

    def resolve_from_module(self, node: ast.ImportFrom) -> Optional[str]:
        """Absolute module path of a ``from X import ...`` statement."""
        if node.level == 0:
            return node.module
        parts = self.package.split(".") if self.package else []
        up = node.level - 1
        if up > len(parts):
            return None
        base_parts = parts[:len(parts) - up] if up else parts
        if node.module:
            base_parts = base_parts + node.module.split(".")
        return ".".join(base_parts) if base_parts else None

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Absolute dotted path of a Name/Attribute chain, or None."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    @staticmethod
    def dotted(node: ast.AST) -> Optional[str]:
        """Literal dotted text of a Name/Attribute chain (``self.model.x``),
        resolver-independent — identity for dataflow-ish checks."""
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            base = ImportResolver.dotted(node.value)
            return f"{base}.{node.attr}" if base else None
        return None


# ---------------------------------------------------------------------------
# source files + suppression
# ---------------------------------------------------------------------------

class SourceFile:
    """One parsed module: text, AST, resolver, suppression map."""

    def __init__(self, root: str, relpath: str, text: Optional[str] = None):
        self.root = root
        self.path = relpath.replace(os.sep, "/")
        if text is None:
            with open(os.path.join(root, relpath), encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=self.path)
        self.resolver = ImportResolver(self.path, self.tree)
        self._suppress = self._parse_suppressions(self.lines)

    @staticmethod
    def _parse_suppressions(lines: Sequence[str]) -> Dict[int, set]:
        """``# tpulint: disable=a,b`` inline suppresses checks a,b on that
        line; on a comment-only line it suppresses them on the NEXT line."""
        out: Dict[int, set] = {}
        for i, line in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            checks = {c.strip() for c in m.group(1).split(",") if c.strip()}
            target = i + 1 if line.split("#", 1)[0].strip() == "" else i
            out.setdefault(target, set()).update(checks)
        return out

    def suppressed(self, line: int, check: str) -> bool:
        s = self._suppress.get(line)
        return bool(s) and (check in s or "all" in s)


# ---------------------------------------------------------------------------
# checker registry
# ---------------------------------------------------------------------------

CHECKERS: Dict[str, "Checker"] = {}


class Checker:
    """Base checker.  Subclasses set ``name``/``description`` and override
    :meth:`check_file` (per-file AST walk), :meth:`check_program` (the
    whole-program pass — receives the shared
    :class:`~.engine.ProgramIndex`, built once per invocation), and/or
    :meth:`check_project` (one run per invocation — live-object probes).
    A project-only checker sets ``reads_files = False`` so a run
    restricted to it (``--only schema-drift``, the shim's mode) skips
    the repo-wide parse — and its parse-error findings — entirely.
    ``needs_engine = True`` asks the runner for the shared call-graph
    index.  ``disk_scoped`` lists repo-relative paths (or glob patterns)
    the checker reads beyond the lint selection — the runner folds them
    into partial runs (``--diff``, explicit paths) and into the result
    cache's content hash so a disk-scoped checker can neither miss its
    context nor serve stale cached verdicts."""

    name = "checker"
    description = ""
    reads_files = True
    needs_engine = False
    disk_scoped: Sequence[str] = ()

    def applies_to(self, path: str) -> bool:
        return True

    def check_file(self, sf: SourceFile) -> Iterable[Finding]:
        return ()

    def check_program(self, index) -> Iterable[Finding]:
        """Whole-program pass over the shared ProgramIndex."""
        return ()

    def check_project(self, files: List[SourceFile]) -> Iterable[Finding]:
        return ()


def register(cls):
    """Class decorator: instantiate and add to the registry."""
    inst = cls()
    assert inst.name not in CHECKERS, f"duplicate checker {inst.name!r}"
    CHECKERS[inst.name] = inst
    return cls


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def iter_py_paths(root: str, paths: Optional[Sequence[str]] = None
                  ) -> List[str]:
    """Repo-relative paths of every ``.py`` under ``paths`` (files or
    dirs), sorted within each root for deterministic output."""
    out: List[str] = []
    for p in (paths or DEFAULT_PATHS):
        full = os.path.join(root, p)
        if os.path.isfile(full):
            out.append(os.path.relpath(full, root))
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(os.path.join(dirpath, fn),
                                               root))
    return out


def collect_files(root: str, paths: Optional[Sequence[str]] = None
                  ) -> List[SourceFile]:
    """Parse every ``.py`` under ``paths``; raises on a syntax error (use
    :func:`run_lint` for the finding-producing wrapper)."""
    return [SourceFile(root, rel) for rel in iter_py_paths(root, paths)]


def run_lint(root: str, paths: Optional[Sequence[str]] = None,
             only: Optional[Sequence[str]] = None,
             disable: Optional[Sequence[str]] = None,
             file_cache: Optional[Dict[str, List["Finding"]]] = None
             ) -> List[Finding]:
    """Run the registered checkers over the file set; returns findings
    sorted by (path, line).  Suppressed findings are dropped here, so
    checkers never need to know about the comment syntax.

    ``file_cache`` (the ``scripts/lint.py`` result cache): per-path
    findings of the FILE-scoped checkers from a previous run over
    byte-identical content — those paths skip :meth:`Checker.check_file`
    and splice the cached findings in (already suppression-filtered,
    since suppression is a function of the unchanged file content).
    Program/project checkers always run live."""
    selected = {n: c for n, c in CHECKERS.items()
                if (only is None or n in only)
                and (disable is None or n not in disable)}
    unknown = [n for n in (list(only or []) + list(disable or []))
               if n not in CHECKERS]
    if unknown:
        raise KeyError(f"unknown checker(s) {unknown}; have "
                       f"{sorted(CHECKERS)}")

    files: List[SourceFile] = []
    findings: List[Finding] = []
    if any(c.reads_files for c in selected.values()):
        for rel in iter_py_paths(root, paths):
            try:
                files.append(SourceFile(root, rel))
            except SyntaxError as e:
                findings.append(Finding(
                    "parse-error", rel.replace(os.sep, "/"),
                    int(e.lineno or 1), 0, f"cannot parse: {e.msg}"))

    index = None
    if files and any(c.needs_engine for c in selected.values()):
        from .engine import ProgramIndex
        index = ProgramIndex(files)

    by_path = {sf.path: sf for sf in files}
    cached_paths = set(file_cache or ())
    for name in sorted(selected):
        checker = selected[name]
        for sf in files:
            if not checker.applies_to(sf.path):
                continue
            if sf.path in cached_paths:
                continue      # spliced in below, once per path
            for f in checker.check_file(sf):
                if not sf.suppressed(f.line, f.check):
                    findings.append(f)
        if index is not None and checker.needs_engine:
            for f in checker.check_program(index):
                sf = by_path.get(f.path)
                if sf is None or not sf.suppressed(f.line, f.check):
                    findings.append(f)
        for f in checker.check_project(files):
            # project-level findings honor the same inline suppression
            # when they anchor to a file the run parsed
            sf = by_path.get(f.path)
            if sf is None or not sf.suppressed(f.line, f.check):
                findings.append(f)
    for path in cached_paths & set(by_path):
        findings.extend(f for f in file_cache[path]
                        if f.check in selected or f.check == "parse-error")
    findings.sort(key=Finding.sort_key)
    return findings


def file_scoped_checkers(selected: Optional[Dict[str, "Checker"]] = None
                         ) -> List[str]:
    """Names of checkers whose findings are a pure function of ONE file
    (overridden :meth:`Checker.check_file`) — the set the per-file
    result cache may memoize."""
    pool = selected if selected is not None else CHECKERS
    return sorted(n for n, c in pool.items()
                  if type(c).check_file is not Checker.check_file)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("entries", []))


def save_baseline(path: str, findings: Sequence[Finding],
                  old_entries: Sequence[dict] = ()) -> List[dict]:
    """Write the baseline deterministically (sorted, path-relative),
    carrying justifications over from matching old entries."""
    just = {}
    for e in old_entries:
        key = (e.get("check"), e.get("path"), e.get("message"))
        just.setdefault(key, []).append(
            e.get("justification", "TODO: justify"))
    entries = []
    for f in sorted(findings, key=lambda f: (f.check, f.path, f.message,
                                             f.line)):
        pool = just.get(f.fingerprint)
        entries.append({
            "check": f.check, "path": f.path, "line": f.line,
            "message": f.message,
            "justification": pool.pop(0) if pool else "TODO: justify",
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")
    return entries


def compare_baseline(findings: Sequence[Finding], entries: Sequence[dict]
                     ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Multiset match on (check, path, message).  Returns
    ``(new, baselined, stale)``: findings not in the baseline, findings
    covered by it, and baseline entries matching nothing (stale)."""
    pool: Dict[Tuple, List[dict]] = {}
    for e in entries:
        key = (e.get("check"), e.get("path"), e.get("message"))
        pool.setdefault(key, []).append(e)
    new: List[Finding] = []
    matched: List[Finding] = []
    for f in findings:
        bucket = pool.get(f.fingerprint)
        if bucket:
            bucket.pop()
            matched.append(f)
        else:
            new.append(f)
    stale = [e for bucket in pool.values() for e in bucket]
    return new, matched, stale
