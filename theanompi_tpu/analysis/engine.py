"""tpulint whole-program engine: repo-wide call graph + summaries.

PR-5's checkers were per-file AST walks — the trace-purity closure
stopped at same-file calls, so a host-side ``time.time()`` hidden one
module away from a ``lax.scan`` body was invisible.  This module builds
ONE :class:`ProgramIndex` per lint invocation on top of the existing
one-parse-per-file :class:`~.core.SourceFile` cache and exposes:

* a **call graph** over every function and method in scope — module
  functions resolved through :class:`~.core.ImportResolver` (relative
  imports included), ``self.<m>``/``cls.<m>`` resolved through the class
  hierarchy INCLUDING subclass overrides (the ``exchange_body`` family),
  and ``obj.<m>`` resolved when the method name is owned by exactly one
  class hierarchy in scope (the *unique-family* rule — ``exchange_body``
  qualifies, ``update`` does not) or when ``obj`` was assigned from a
  visible constructor.  Callables passed by keyword or decorator count
  as references (an edge), matching how trace wrappers consume them.
* **transitive reachability** (:meth:`ProgramIndex.reachable`) so a
  checker can close a seed set over the whole repo instead of one file.
* a **per-function summary lattice** (:class:`FuncSummary`, all facts
  monotone unions): reads-host-state, consumes-key (which parameter
  positions a function spends as jax.random keys — directly or by
  passing them into a consuming callee), issues-collective (which
  ``lax`` collectives with which statically-known axis names), donates.
  :meth:`ProgramIndex.transitive_summary` unions a function's summary
  over everything it can reach.
* **thread-role inference** (round 15, docs/design.md §16): every host
  concurrency entry point in scope — ``threading.Thread(target=…)`` /
  ``Timer``, ``run()`` overrides of ``threading.Thread`` subclasses,
  ``signal.signal`` handlers, ``atexit`` hooks, ``socketserver``
  request-handler classes, executor ``submit``/``add_done_callback``
  callables — becomes a :class:`ThreadRole` whose members are the
  functions reachable from its entry, PLUS the implicit ``main`` role
  (everything reachable from non-entry top-level functions/methods).
  Role closures cut the *spawn edges*: referencing ``self._producer``
  at a ``Thread(target=self._producer)`` site hands the function to the
  new thread, it does not call it on the spawning one — so a producer
  body stays out of ``main`` unless something actually calls it there.
  :meth:`ProgramIndex.role_map` is what the host-concurrency checkers
  (shared-state-race, lock-ordering, signal-safety, daemon-discipline)
  consume.

The engine is deliberately STATIC-only (stdlib ``ast``): resolution that
would need type inference returns the empty list rather than guessing —
a checker migrating onto this API keeps per-file behavior on single-file
fixture runs (cross-file targets simply are not in scope) and gains the
interprocedural closure on repo-wide runs.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from .core import ImportResolver, SourceFile

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)
_FuncLike = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# ---------------------------------------------------------------------------
# shared vocabulary (checkers import these instead of re-declaring)
# ---------------------------------------------------------------------------

HOST_CLOCKS = {"time.time", "time.perf_counter", "time.monotonic",
               "time.process_time", "time.sleep"}
SYNC_CALLS = {"jax.device_get"}

# jax.random.<fn> that CONSUME their key argument (split consumes: two
# splits of one key collide; fold_in derives and is deliberately absent —
# the §8 fused-cadence contract).
KEY_CONSUMERS = {
    "ball", "bernoulli", "beta", "binomial", "bits", "categorical",
    "cauchy", "chisquare", "choice", "dirichlet", "double_sided_maxwell",
    "exponential", "f", "gamma", "generalized_normal", "geometric",
    "gumbel", "laplace", "loggamma", "logistic", "lognormal", "maxwell",
    "multinomial", "multivariate_normal", "normal", "orthogonal",
    "pareto", "permutation", "poisson", "rademacher", "randint",
    "rayleigh", "split", "t", "triangular", "truncated_normal",
    "uniform", "wald", "weibull_min",
}

# named-axis collectives: maps the simple name to the positional index of
# the axis-name argument in the jax.lax signature
COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
    "ppermute": 1, "pshuffle": 1, "all_gather": 1,
    "all_gather_invariant": 1, "all_to_all": 1, "psum_scatter": 1,
    "axis_index": 0, "axis_size": 0,
    # async start halves (the bucketed-wire shims in jax_compat — their
    # `_done` twins take a ticket, not an axis, and are covered by the
    # collective-discipline pairing probe instead)
    "psum_start": 1, "all_gather_start": 1, "ppermute_start": 1,
}

_COLLECTIVE_MODULES = ("jax.lax.", "theanompi_tpu.jax_compat.")


def collective_name(resolved: Optional[str]) -> Optional[str]:
    """The simple collective name of a resolved dotted path, or None."""
    if not resolved:
        return None
    for mod in _COLLECTIVE_MODULES:
        if resolved.startswith(mod):
            simple = resolved[len(mod):]
            if simple in COLLECTIVES:
                return simple
    return None


# ---------------------------------------------------------------------------
# compile-surface vocabulary (docs/design.md §26)
# ---------------------------------------------------------------------------

#: Receiver names whose subscripts / ``.get`` reads count as config-knob
#: reads: the tail of the dotted receiver (``config``, ``self.config``,
#: ``model.config``, ``cfg``) — plus any local assigned from ``parse_kv``
#: (the caller passes those in as ``extra_receivers``).
CONFIG_RECEIVERS = {"config", "cfg"}

#: Trace-shaping consumer slots that must be STATIC at trace time — a
#: host value landing here changes the traced program's shape (scan
#: lengths, schedule tables, iota/zeros shapes, PartitionSpecs, jit
#: donation/static signatures).  ``"all"`` marks every argument;
#: otherwise a tuple of positional indices and keyword names.
TRACE_SHAPE_SLOTS = {
    "jax.lax.scan": ("length",),
    "jax.numpy.arange": "all",
    "jax.numpy.zeros": "all",
    "jax.numpy.ones": "all",
    "jax.numpy.full": (0, "shape"),
    "jax.numpy.eye": "all",
    "jax.numpy.reshape": (1, "newshape", "shape"),
    "numpy.arange": "all",
    "numpy.zeros": "all",
    "numpy.ones": "all",
    "numpy.full": (0, "shape"),
    "jax.sharding.PartitionSpec": "all",
    "theanompi_tpu.jax_compat.P": "all",
    # repo-local schedule/plan builders: their scalar arguments bake
    # host-side tables into the traced program (docs/design.md §26)
    "theanompi_tpu.parallel.pipeline.build_schedule": "all",
    "theanompi_tpu.parallel.update_sharding.plan_tree": "all",
    "theanompi_tpu.parallel.buckets.plan_buckets": (1, "bucket_bytes"),
}

#: Predicate/selector slots: traced values are LEGAL here (``lax.cond``
#: runs both branches), but a config knob baked into one still selects
#: program behavior per compile — so the cache-key pass treats them as
#: trace-shaping while the retrace pass does not.
TRACE_PRED_SLOTS = {
    "jax.lax.cond": (0, "pred"),
    "jax.lax.switch": (0, "index"),
    "jax.lax.fori_loop": (0, 1, "lower", "upper"),
}

#: Method names whose arguments are shape slots on any receiver.
TRACE_SHAPE_METHODS = {"reshape", "broadcast_to"}

#: ``jax.jit`` keywords whose values shape the compiled signature.
TRACE_JIT_KWARGS = {"static_argnums", "static_argnames",
                    "donate_argnums", "donate_argnames"}

#: Attribute reads that are aval-static on a tracer — ``x.shape[0]`` in
#: a reshape is shape arithmetic over the ALREADY-compiled signature,
#: not a host value, so their bases never count as shaping uses.
AVAL_ATTRS = {"shape", "ndim", "size", "dtype"}

#: Dtypes the dtype-flow pass treats as low-precision wire formats.
LOW_PRECISION_DTYPES = {"bfloat16", "float16", "float8_e4m3fn",
                        "float8_e5m2"}

_DTYPE_MODULES = ("jax.numpy.", "numpy.", "jax.dtypes.")


def config_knob(node: ast.AST,
                extra_receivers: Optional[Set[str]] = None
                ) -> Optional[str]:
    """The knob string of a config read expression — ``config["x"]``,
    ``cfg.get("x", d)``, ``self.config.get("x")`` — or None.  A dotted
    receiver matches when its last segment is in :data:`CONFIG_RECEIVERS`
    or the whole chain is in ``extra_receivers`` (parse_kv locals)."""
    recv = key = None
    if isinstance(node, ast.Subscript):
        recv = node.value
        if isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str):
            key = node.slice.value
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr == "get" and node.args:
        recv = node.func.value
        a0 = node.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            key = a0.value
    if recv is None or key is None:
        return None
    dotted = ImportResolver.dotted(recv)
    if dotted is None:
        return None
    if dotted.rsplit(".", 1)[-1] in CONFIG_RECEIVERS or \
            (extra_receivers and dotted in extra_receivers):
        return key
    return None


def shaping_slot_exprs(call: ast.Call, resolver: ImportResolver,
                       preds: bool = True):
    """``(expr, slot description)`` for every argument of ``call``
    occupying a trace-shaping slot.  ``preds=False`` restricts to the
    shape-static slots (the retrace pass)."""
    resolved = resolver.resolve(call.func)
    out = []

    def take(slots, label):
        if slots == "all":
            for a in call.args:
                out.append((a, label))
            for kw in call.keywords:
                if kw.arg is not None:
                    out.append((kw.value, label))
            return
        for s in slots:
            if isinstance(s, int):
                if s < len(call.args):
                    out.append((call.args[s], label))
            else:
                for kw in call.keywords:
                    if kw.arg == s:
                        out.append((kw.value, label))

    if resolved in TRACE_SHAPE_SLOTS:
        take(TRACE_SHAPE_SLOTS[resolved], f"`{resolved.rsplit('.', 1)[-1]}`")
    elif preds and resolved in TRACE_PRED_SLOTS:
        take(TRACE_PRED_SLOTS[resolved], f"`{resolved.rsplit('.', 1)[-1]}`")
    elif resolved == "jax.jit":
        for kw in call.keywords:
            if kw.arg in TRACE_JIT_KWARGS:
                out.append((kw.value, f"`jax.jit({kw.arg}=…)`"))
    elif resolved is None and isinstance(call.func, ast.Attribute) and \
            call.func.attr in TRACE_SHAPE_METHODS:
        for a in call.args:
            out.append((a, f"`.{call.func.attr}()`"))
    return out


def bare_names(expr: ast.AST) -> List[ast.Name]:
    """Name loads in ``expr``, excluding bases of aval-attribute chains
    (``x.shape[0]`` is static per-aval, not a host value)."""
    out: List[ast.Name] = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in AVAL_ATTRS:
            return
        if isinstance(n, ast.Name):
            out.append(n)
        for c in ast.iter_child_nodes(n):
            visit(c)

    visit(expr)
    return out


def static_dtype(node: ast.AST, resolver: ImportResolver
                 ) -> Optional[str]:
    """The simple dtype name of a statically-resolved dtype expression
    (``jnp.bfloat16``, ``np.float16``, ``"bfloat16"``), else None —
    dynamic wire dtypes (``self.wire_dtype``) resolve to nothing and are
    deliberately not guessed."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    resolved = resolver.resolve(node)
    if resolved:
        for mod in _DTYPE_MODULES:
            if resolved.startswith(mod):
                return resolved[len(mod):]
    return None


# ---------------------------------------------------------------------------
# records and summaries
# ---------------------------------------------------------------------------

@dataclass
class FuncRecord:
    """One function/method definition anywhere in scope."""

    sf: SourceFile
    node: ast.AST                      # FunctionDef/AsyncFunctionDef/Lambda
    qualname: str                      # module.Class.method / module.func
    class_name: Optional[str] = None   # simple name of the enclosing class
    class_key: Optional[Tuple[str, str]] = None   # (module, ClassName)

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")

    def params(self) -> List[str]:
        a = self.node.args
        out = [p.arg for p in list(a.posonlyargs) + list(a.args)]
        return [p for p in out if p not in ("self", "cls")]


@dataclass
class FuncSummary:
    """Direct (non-transitive) facts about one function body.  Every
    field is a monotone set/flag so transitive summaries are unions."""

    host_calls: List[Tuple[ast.AST, str]] = field(default_factory=list)
    key_params: Set[int] = field(default_factory=set)
    collectives: List[Tuple[ast.AST, str, Tuple]] = field(
        default_factory=list)        # (call node, name, axis values or ())
    donates: bool = False

    @property
    def reads_host_state(self) -> bool:
        return bool(self.host_calls)

    @property
    def consumes_key(self) -> bool:
        return bool(self.key_params)

    @property
    def issues_collective(self) -> bool:
        return bool(self.collectives)


@dataclass
class TransitiveSummary:
    reads_host_state: bool = False
    consumes_key: bool = False
    issues_collective: bool = False
    donates: bool = False
    collective_names: FrozenSet[str] = frozenset()


# ---------------------------------------------------------------------------
# thread roles (host-concurrency pass, docs/design.md §16)
# ---------------------------------------------------------------------------

#: The implicit role every function belongs to unless it is ONLY reachable
#: through a spawn edge (a ``Thread(target=…)`` reference, a signal
#: handler registration, …).
MAIN_ROLE = "main"

# spawn-construct vocabulary: resolved callable -> (kind, how to find the
# entry expression).  ``signal.signal(sig, h)``'s handler is positional 1;
# ``atexit.register(f)``'s is positional 0; Thread/Timer take keyword
# ``target``/``function`` (or the documented positional slot).
_SPAWN_CTORS = {
    "threading.Thread": ("thread", 1, ("target",)),
    "threading.Timer": ("timer", 1, ("function",)),
}
_SPAWN_REGISTRARS = {
    "signal.signal": ("signal", 1, ("handler",)),
    "atexit.register": ("atexit", 0, ()),
}
# receiver methods that hand a callable to another thread
_SPAWN_METHODS = {"submit": ("executor", 0),
                  "add_done_callback": ("executor", 0)}
_NON_HANDLERS = {"signal.SIG_DFL", "signal.SIG_IGN", "signal.default_int_handler"}

#: Method names so common on stdlib objects (threads, locks, sockets,
#: files, processes) that the unique-family fallback must not claim them
#: during ROLE closure: `t.join()` on a Thread resolving to the one
#: in-scope class that happens to define `join` would teleport that
#: class's methods into the spawning role.  Precise resolution paths
#: (self., ctor-typed receivers, imports) are unaffected.
GENERIC_METHOD_NAMES = {
    "join", "start", "stop", "run", "close", "wait", "get", "put",
    "set", "clear", "pop", "read", "write", "flush", "send", "recv",
    "sendall", "accept", "connect", "acquire", "release", "poll",
    "kill", "terminate", "shutdown", "submit", "result", "cancel",
    "items", "keys", "values", "update", "copy", "append", "add",
    "remove", "beat",
}

#: Base classes whose subclasses' ``run`` (Thread) / ``handle``
#: (socketserver) methods execute on their own thread.
THREAD_BASES = ("threading.Thread", "threading.Timer")
HANDLER_BASES = ("socketserver.BaseRequestHandler",
                 "socketserver.StreamRequestHandler",
                 "socketserver.DatagramRequestHandler")


@dataclass
class SpawnSite:
    """One place a concurrency entry point is introduced: a
    ``Thread``/``Timer`` construction, a handler registration, a
    thread-subclass / request-handler class definition."""

    sf: SourceFile
    node: ast.AST                 # the Call or ClassDef
    kind: str                     # thread|timer|signal|atexit|executor|
    #                               thread-subclass|handler
    target_desc: str              # source text of the entry expression
    entries: List[FuncRecord] = field(default_factory=list)

    @property
    def path(self) -> str:
        return self.sf.path

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 1)


@dataclass
class ThreadRole:
    """One inferred thread role: a name, its kind, every spawn site that
    introduces it, and its entry records (members come from
    :meth:`ProgramIndex.role_members`)."""

    name: str
    kind: str
    sites: List[SpawnSite] = field(default_factory=list)
    entries: List[FuncRecord] = field(default_factory=list)


# ---------------------------------------------------------------------------
# per-file scope index
# ---------------------------------------------------------------------------

class FileIndex:
    """Scoping structure of one file: defs by enclosing function scope,
    methods by class, classes with their (resolved) base names, and the
    enclosing function of every node."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        # id(scope func node or None) -> {name: [def nodes]}
        self.by_scope: Dict[Optional[int], Dict[str, List[ast.AST]]] = {}
        # method simple name -> [def nodes] across every class in the file
        self.methods: Dict[str, List[ast.AST]] = {}
        # def-node id -> enclosing function node
        self.parent_func: Dict[int, Optional[ast.AST]] = {}
        # any-node id -> enclosing function node (call-site scope lookup)
        self.enclosing: Dict[int, Optional[ast.AST]] = {}
        # ClassDef nodes by simple name; def-node id -> owning ClassDef
        self.classes: Dict[str, ast.ClassDef] = {}
        self.class_of: Dict[int, ast.ClassDef] = {}
        self._walk(sf.tree, None, None)
        self._record_enclosing(sf.tree, None)

    def _walk(self, node, func, cls) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FuncDef):
                scope = self.by_scope.setdefault(
                    id(func) if func else None, {})
                scope.setdefault(child.name, []).append(child)
                if cls is not None and isinstance(node, ast.ClassDef):
                    self.methods.setdefault(child.name, []).append(child)
                    self.class_of[id(child)] = cls
                self.parent_func[id(child)] = func
                self._walk(child, child, None)
            elif isinstance(child, ast.ClassDef):
                self.classes.setdefault(child.name, child)
                self._walk(child, func, child)
            elif isinstance(child, ast.Lambda):
                self.parent_func[id(child)] = func
                self._walk(child, child, None)
            else:
                self._walk(child, func, cls)

    def _record_enclosing(self, node, func) -> None:
        self.enclosing[id(node)] = func
        for child in ast.iter_child_nodes(node):
            self._record_enclosing(
                child, child if isinstance(child, _FuncLike) else func)

    def lookup(self, name: str, from_func: Optional[ast.AST]
               ) -> List[ast.AST]:
        """Defs named ``name`` visible from ``from_func``: its locals,
        then enclosing functions', then module level."""
        f = from_func
        while True:
            scope = self.by_scope.get(id(f) if f else None, {})
            if name in scope:
                return list(scope[name])
            if f is None:
                return []
            f = self.parent_func.get(id(f))


# ---------------------------------------------------------------------------
# the whole-program index
# ---------------------------------------------------------------------------

class ProgramIndex:
    """Repo-wide call graph + summaries over a list of parsed files."""

    def __init__(self, files: Sequence[SourceFile]):
        self.files = list(files)
        self.by_path: Dict[str, SourceFile] = {sf.path: sf for sf in files}
        self.file_index: Dict[str, FileIndex] = {
            sf.path: FileIndex(sf) for sf in files}
        # absolute dotted name -> [FuncRecord] (module funcs AND methods
        # under module.Class.method)
        self.by_qualname: Dict[str, List[FuncRecord]] = {}
        # method simple name -> [FuncRecord] repo-wide
        self.methods: Dict[str, List[FuncRecord]] = {}
        self.records: Dict[int, FuncRecord] = {}      # id(node) -> record
        # (module, ClassName) -> [absolute dotted base names]
        self.class_bases: Dict[Tuple[str, str], List[str]] = {}
        # absolute dotted class name -> (module, ClassName)
        self._class_keys: Dict[str, Tuple[str, str]] = {}
        self._module_constants: Dict[str, object] = {}
        for sf in files:
            self._index_file(sf)
        self._subclasses = self._compute_subclasses()
        self._callees_cache: Dict[int, List[FuncRecord]] = {}
        self._summary_cache: Dict[int, FuncSummary] = {}
        self._key_params_cache: Optional[Dict[int, Set[int]]] = None
        self._shaping_params_cache: Dict[bool, Dict[int, Set[int]]] = {}
        self._transitive_cache: Dict[int, TransitiveSummary] = {}

    # -- construction ------------------------------------------------------

    def _index_file(self, sf: SourceFile) -> None:
        module = sf.resolver.module
        idx = self.file_index[sf.path]
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Lambda):
                # unnamed, unresolvable by name — indexed so a lambda
                # seed (a scan body) still closes over its callees
                rec = FuncRecord(sf, node,
                                 f"{module}.<lambda>:{node.lineno}")
                self.records[id(node)] = rec
                continue
            if not isinstance(node, _FuncDef):
                continue
            cls = idx.class_of.get(id(node))
            if cls is not None:
                qual = f"{module}.{cls.name}.{node.name}"
                rec = FuncRecord(sf, node, qual, cls.name,
                                 (module, cls.name))
                self.methods.setdefault(node.name, []).append(rec)
            elif idx.parent_func.get(id(node)) is None:
                qual = f"{module}.{node.name}"
                rec = FuncRecord(sf, node, qual)
            else:
                qual = f"{module}.<locals>.{node.name}"
                rec = FuncRecord(sf, node, qual)
            self.records[id(node)] = rec
            self.by_qualname.setdefault(rec.qualname, []).append(rec)
        for name, cls in idx.classes.items():
            key = (module, name)
            self._class_keys[f"{module}.{name}"] = key
            bases = []
            for b in cls.bases:
                resolved = sf.resolver.resolve(b)
                if resolved is None and isinstance(b, ast.Name):
                    # same-file base class
                    if b.id in idx.classes:
                        resolved = f"{module}.{b.id}"
                if resolved:
                    bases.append(resolved)
            self.class_bases[key] = bases
        # module-level constants: strings (mesh axis names and the like)
        # and tuples of constants (event vocabularies — MEMBERSHIP_EVENTS,
        # STATUSZ_OPS, RULE_ACTIONS — the protocol checkers read these
        # statically).  Consumers filter by type, so adding tuples here
        # cannot change the axis-name evaluation (isinstance(v, str)).
        for st in sf.tree.body:
            if not isinstance(st, ast.Assign):
                continue
            value = None
            if isinstance(st.value, ast.Constant):
                value = st.value.value
            elif isinstance(st.value, (ast.Tuple, ast.List)) and \
                    all(isinstance(e, ast.Constant) for e in st.value.elts):
                value = tuple(e.value for e in st.value.elts)
            if value is None:
                continue
            for t in st.targets:
                if isinstance(t, ast.Name):
                    self._module_constants[f"{module}.{t.id}"] = value

    def _compute_subclasses(self) -> Dict[Tuple[str, str],
                                          Set[Tuple[str, str]]]:
        """Transitive subclass sets, keyed by (module, ClassName)."""
        direct: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for key, bases in self.class_bases.items():
            for b in bases:
                bkey = self._class_keys.get(b)
                if bkey is not None:
                    direct.setdefault(bkey, set()).add(key)
        out: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}

        def close(key):
            if key in out:
                return out[key]
            out[key] = set()
            for sub in direct.get(key, ()):
                out[key].add(sub)
            # iterate to fixpoint below instead of recursing (cycles)
            return out[key]

        for key in list(self.class_bases):
            close(key)
        changed = True
        while changed:
            changed = False
            for key, subs in out.items():
                grown = set(subs)
                for s in subs:
                    grown |= out.get(s, set())
                if grown != subs:
                    out[key] = grown
                    changed = True
        return out

    # -- class hierarchy queries ------------------------------------------

    def module_constant(self, dotted: str):
        """The literal value of a module-level constant, or None."""
        return self._module_constants.get(dotted)

    def subclasses_of(self, dotted_class: str) -> List[Tuple[str, str]]:
        key = self._class_keys.get(dotted_class)
        if key is None:
            return []
        return sorted(self._subclasses.get(key, set()) | {key})

    def hierarchy_root(self, key: Tuple[str, str]) -> Tuple[str, str]:
        """Topmost in-scope ancestor of a class (first-base chain)."""
        seen = set()
        while key not in seen:
            seen.add(key)
            bases = self.class_bases.get(key, [])
            parent = None
            for b in bases:
                bkey = self._class_keys.get(b)
                if bkey is not None:
                    parent = bkey
                    break
            if parent is None:
                return key
            key = parent
        return key

    def method_records(self, class_key: Tuple[str, str], name: str,
                       include_subclasses: bool = True) -> List[FuncRecord]:
        """Records for ``name`` defined on the class, its in-scope
        ancestors, and (optionally) every subclass override."""
        keys = {class_key}
        # ancestors (first-base chains, all bases)
        frontier = [class_key]
        while frontier:
            k = frontier.pop()
            for b in self.class_bases.get(k, []):
                bk = self._class_keys.get(b)
                if bk is not None and bk not in keys:
                    keys.add(bk)
                    frontier.append(bk)
        if include_subclasses:
            keys |= self._subclasses.get(class_key, set())
            # overrides live on subclasses of ANCESTORS too (siblings are
            # deliberately excluded: a sibling's override is unreachable
            # through this receiver)
        out = []
        for k in keys:
            out.extend(self.by_qualname.get(f"{k[0]}.{k[1]}.{name}", []))
        return out

    # -- call resolution ---------------------------------------------------

    def _unique_family(self, name: str) -> List[FuncRecord]:
        """All methods named ``name`` when they belong to ONE class
        hierarchy (same root) — the ``exchange_body`` rule.  Ambiguous
        names (``update``, ``init``) resolve to nothing."""
        recs = self.methods.get(name, [])
        if not recs:
            return []
        roots = {self.hierarchy_root(r.class_key) for r in recs
                 if r.class_key is not None}
        if len(roots) != 1:
            return []
        return list(recs)

    def _local_ctor_types(self, rec: FuncRecord) -> Dict[str, Tuple[str,
                                                                    str]]:
        """Names assigned from a visible constructor call in this
        function's body: ``exch = BSP_Exchanger(cfg)`` -> class key."""
        out: Dict[str, Tuple[str, str]] = {}
        for sub in body_walk(rec.node):
            if not isinstance(sub, ast.Assign) or \
                    not isinstance(sub.value, ast.Call):
                continue
            fn = sub.value.func
            cls_key = None
            if isinstance(fn, ast.Name):
                idx = self.file_index[rec.sf.path]
                if fn.id in idx.classes:
                    cls_key = (rec.sf.resolver.module, fn.id)
                else:
                    resolved = rec.sf.resolver.resolve(fn)
                    cls_key = self._class_keys.get(resolved or "")
            else:
                resolved = rec.sf.resolver.resolve(fn)
                cls_key = self._class_keys.get(resolved or "")
            if cls_key is None:
                continue
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = cls_key
        return out

    def resolve_call(self, sf: SourceFile, func_expr: ast.AST,
                     enclosing: Optional[ast.AST],
                     ctor_types: Optional[Dict[str, Tuple[str, str]]] = None,
                     skip_generic_unique: bool = False
                     ) -> List[FuncRecord]:
        """Possible targets of a call through ``func_expr``, or [].
        ``skip_generic_unique`` (role closures) withholds the
        unique-family fallback for :data:`GENERIC_METHOD_NAMES`."""
        idx = self.file_index[sf.path]
        if isinstance(func_expr, ast.Name):
            local = idx.lookup(func_expr.id, enclosing)
            if local:
                return [self.records[id(n)] for n in local
                        if id(n) in self.records]
            resolved = sf.resolver.resolve(func_expr)
            if resolved:
                return list(self.by_qualname.get(resolved, []))
            return []
        if isinstance(func_expr, ast.Attribute):
            base = func_expr.value
            # self.m / cls.m: the enclosing class hierarchy + overrides
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                cls = None
                f = enclosing
                while f is not None:
                    cls = idx.class_of.get(id(f))
                    if cls is not None:
                        break
                    f = idx.parent_func.get(id(f))
                if cls is not None:
                    recs = self.method_records(
                        (sf.resolver.module, cls.name), func_expr.attr)
                    if recs:
                        return recs
                # fixtures sometimes call self.m outside an indexed class;
                # fall back to same-file methods by name
                return [self.records[id(n)]
                        for n in idx.methods.get(func_expr.attr, [])
                        if id(n) in self.records]
            # module.func through the import resolver
            resolved = sf.resolver.resolve(func_expr)
            if resolved and resolved in self.by_qualname:
                return list(self.by_qualname[resolved])
            # receiver with a locally-visible constructor type
            if isinstance(base, ast.Name) and ctor_types and \
                    base.id in ctor_types:
                return self.method_records(ctor_types[base.id],
                                           func_expr.attr)
            # self.<attr>.<m>() where the attr was assigned from a
            # visible constructor anywhere in the enclosing class
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id in ("self", "cls"):
                cls = None
                f = enclosing
                while f is not None:
                    cls = idx.class_of.get(id(f))
                    if cls is not None:
                        break
                    f = idx.parent_func.get(id(f))
                if cls is not None:
                    ctor = self.class_attr_ctors(
                        (sf.resolver.module, cls.name)).get(base.attr)
                    key = self._class_keys.get(ctor or "")
                    if key is not None:
                        return self.method_records(key, func_expr.attr)
            # unique-family method name (the exchange_body rule)
            if skip_generic_unique and \
                    func_expr.attr in GENERIC_METHOD_NAMES:
                return []
            return self._unique_family(func_expr.attr)
        return []

    def callees(self, rec: FuncRecord) -> List[FuncRecord]:
        """Direct call/reference targets of one function body (not
        descending into nested defs — they are reachable when called,
        and local calls resolve through the scope chain)."""
        cached = self._callees_cache.get(id(rec.node))
        if cached is not None:
            return cached
        idx = self.file_index[rec.sf.path]
        ctor_types = self._local_ctor_types(rec)
        out: List[FuncRecord] = []
        seen: Set[int] = set()

        def add(targets: Iterable[FuncRecord]) -> None:
            for t in targets:
                if id(t.node) not in seen and t.node is not rec.node:
                    seen.add(id(t.node))
                    out.append(t)

        for sub in body_walk(rec.node):
            if isinstance(sub, ast.Call):
                enc = idx.enclosing.get(id(sub.func), rec.node)
                add(self.resolve_call(rec.sf, sub.func, enc, ctor_types))
                # callables passed as arguments are references too
                for arg in list(sub.args) + [kw.value for kw in
                                             sub.keywords]:
                    if isinstance(arg, (ast.Name, ast.Attribute)):
                        enc = idx.enclosing.get(id(arg), rec.node)
                        add(self.resolve_call(rec.sf, arg, enc,
                                              ctor_types))
        self._callees_cache[id(rec.node)] = out
        return out

    def reachable(self, seeds: Iterable[FuncRecord]) -> List[FuncRecord]:
        """Transitive closure of :meth:`callees` over the seed set
        (seeds included)."""
        out: List[FuncRecord] = []
        seen: Set[int] = set()
        frontier = list(seeds)
        while frontier:
            rec = frontier.pop()
            if id(rec.node) in seen:
                continue
            seen.add(id(rec.node))
            out.append(rec)
            frontier.extend(self.callees(rec))
        return out

    def record_for(self, node: ast.AST) -> Optional[FuncRecord]:
        return self.records.get(id(node))

    # -- summaries ---------------------------------------------------------

    def summary(self, rec: FuncRecord) -> FuncSummary:
        """Direct facts about one function body (cached)."""
        cached = self._summary_cache.get(id(rec.node))
        if cached is not None:
            return cached
        s = FuncSummary()
        resolver = rec.sf.resolver
        params = [p for p in rec.params()]
        for sub in body_walk(rec.node):
            if not isinstance(sub, ast.Call):
                continue
            resolved = resolver.resolve(sub.func)
            if resolved in HOST_CLOCKS:
                s.host_calls.append((sub, f"host clock `{resolved}()`"))
            elif resolved and resolved.startswith("numpy.random."):
                s.host_calls.append((sub, f"host RNG `{resolved}()`"))
            elif resolved in SYNC_CALLS:
                s.host_calls.append((sub, f"`{resolved}()`"))
            cname = collective_name(resolved)
            if cname is not None:
                s.collectives.append(
                    (sub, cname, axis_values(sub, cname, resolver, self)))
            if resolved == "jax.jit" and any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in sub.keywords):
                s.donates = True
            # direct key consumption of a parameter
            kn = consumed_key_name(sub, resolver)
            if kn is not None and kn in params:
                s.key_params.add(params.index(kn))
        self._summary_cache[id(rec.node)] = s
        return s

    def key_params(self, rec: FuncRecord) -> Set[int]:
        """Parameter positions this function consumes as jax.random keys
        — directly, or by passing them to a consuming callee (fixpoint
        across the whole graph)."""
        if self._key_params_cache is None:
            self._key_params_cache = self._compute_key_params()
        return self._key_params_cache.get(id(rec.node), set())

    def _compute_key_params(self) -> Dict[int, Set[int]]:
        out: Dict[int, Set[int]] = {}
        for rec in self.records.values():
            direct = self.summary(rec).key_params
            if direct:
                out[id(rec.node)] = set(direct)
        changed = True
        while changed:
            changed = False
            for rec in self.records.values():
                params = rec.params()
                idx = self.file_index[rec.sf.path]
                ctor_types = None
                for sub in body_walk(rec.node):
                    if not isinstance(sub, ast.Call):
                        continue
                    enc = idx.enclosing.get(id(sub.func), rec.node)
                    if ctor_types is None:
                        ctor_types = self._local_ctor_types(rec)
                    for tgt in self.resolve_call(rec.sf, sub.func, enc,
                                                 ctor_types):
                        tgt_kp = out.get(id(tgt.node))
                        if not tgt_kp:
                            continue
                        tparams = tgt.params()
                        for i in tgt_kp:
                            arg = None
                            if i < len(sub.args):
                                arg = sub.args[i]
                            for kw in sub.keywords:
                                if i < len(tparams) and \
                                        kw.arg == tparams[i]:
                                    arg = kw.value
                            if isinstance(arg, ast.Name) and \
                                    arg.id in params:
                                j = params.index(arg.id)
                                cur = out.setdefault(id(rec.node), set())
                                if j not in cur:
                                    cur.add(j)
                                    changed = True
        return out

    def shaping_params(self, rec: FuncRecord, preds: bool = True
                       ) -> Set[int]:
        """Parameter positions this function spends in trace-shaping
        slots — directly, or by passing them into a callee that does
        (fixpoint, like :meth:`key_params`).  ``preds=False`` restricts
        to the shape-static slots (the retrace-hazard pass); the default
        also counts predicate/selector slots (the cache-key pass)."""
        if preds not in self._shaping_params_cache:
            self._shaping_params_cache[preds] = \
                self._compute_shaping_params(preds)
        return self._shaping_params_cache[preds].get(id(rec.node), set())

    def _compute_shaping_params(self, preds: bool) -> Dict[int, Set[int]]:
        out: Dict[int, Set[int]] = {}
        for rec in self.records.values():
            params = rec.params()
            if not params:
                continue
            direct: Set[int] = set()
            for sub in body_walk(rec.node):
                if not isinstance(sub, ast.Call):
                    continue
                for expr, _why in shaping_slot_exprs(sub, rec.sf.resolver,
                                                     preds=preds):
                    for nm in bare_names(expr):
                        if nm.id in params:
                            direct.add(params.index(nm.id))
            if direct:
                out[id(rec.node)] = direct
        changed = True
        while changed:
            changed = False
            for rec in self.records.values():
                params = rec.params()
                if not params:
                    continue
                idx = self.file_index[rec.sf.path]
                ctor_types = None
                for sub in body_walk(rec.node):
                    if not isinstance(sub, ast.Call):
                        continue
                    enc = idx.enclosing.get(id(sub.func), rec.node)
                    if ctor_types is None:
                        ctor_types = self._local_ctor_types(rec)
                    for tgt in self.resolve_call(rec.sf, sub.func, enc,
                                                 ctor_types):
                        tgt_sp = out.get(id(tgt.node))
                        if not tgt_sp:
                            continue
                        tparams = tgt.params()
                        for i in tgt_sp:
                            arg = sub.args[i] if i < len(sub.args) else None
                            for kw in sub.keywords:
                                if i < len(tparams) and \
                                        kw.arg == tparams[i]:
                                    arg = kw.value
                            if arg is None:
                                continue
                            for nm in bare_names(arg):
                                if nm.id in params:
                                    j = params.index(nm.id)
                                    cur = out.setdefault(id(rec.node),
                                                         set())
                                    if j not in cur:
                                        cur.add(j)
                                        changed = True
        return out

    def shaping_use_sites(self, rec: FuncRecord, preds: bool = True,
                          deep: bool = False):
        """``(expr, why)`` for every expression in ``rec``'s body that
        occupies a trace-shaping slot — the direct consumer slots plus
        arguments feeding a callee parameter the callee spends in one.
        ``deep=True`` walks nested defs too (closure-variable flows:
        a knob read at build level consumed inside the traced inner
        function)."""
        idx = self.file_index[rec.sf.path]
        resolver = rec.sf.resolver
        ctor_types = None
        out = []
        walk = ast.walk(rec.node) if deep else body_walk(rec.node)
        for sub in walk:
            if not isinstance(sub, ast.Call):
                continue
            out.extend(shaping_slot_exprs(sub, resolver, preds=preds))
            enc = idx.enclosing.get(id(sub.func), rec.node)
            if ctor_types is None:
                ctor_types = self._local_ctor_types(rec)
            for tgt in self.resolve_call(rec.sf, sub.func, enc,
                                         ctor_types):
                sp = self.shaping_params(tgt, preds=preds)
                if not sp:
                    continue
                tparams = tgt.params()
                for i in sp:
                    arg = sub.args[i] if i < len(sub.args) else None
                    for kw in sub.keywords:
                        if i < len(tparams) and kw.arg == tparams[i]:
                            arg = kw.value
                    if arg is not None:
                        out.append(
                            (arg, f"`{tgt.name}({tparams[i]}=…)`"))
        return out

    def transitive_summary(self, rec: FuncRecord) -> TransitiveSummary:
        """Union of :meth:`summary` over everything reachable from
        ``rec`` (cached)."""
        cached = self._transitive_cache.get(id(rec.node))
        if cached is not None:
            return cached
        t = TransitiveSummary()
        names: Set[str] = set()
        for r in self.reachable([rec]):
            s = self.summary(r)
            t.reads_host_state = t.reads_host_state or s.reads_host_state
            t.consumes_key = t.consumes_key or s.consumes_key
            t.issues_collective = t.issues_collective or \
                s.issues_collective
            t.donates = t.donates or s.donates
            names.update(n for _, n, _ in s.collectives)
        t.collective_names = frozenset(names)
        self._transitive_cache[id(rec.node)] = t
        return t

    # -- thread roles (host-concurrency pass) -------------------------------

    def resolve_callable(self, sf: SourceFile, expr: ast.AST,
                         enclosing: Optional[ast.AST],
                         ctor_types=None,
                         _seen_names: Optional[Set[str]] = None
                         ) -> List[FuncRecord]:
        """Targets of a callable-valued expression — :meth:`resolve_call`
        plus the spawn-site idioms: an inline ``lambda``, and a local
        Name bound from an assignment or a ``for``-loop over a literal
        tuple of method references (the ChaosProxy pump-pair shape).
        ``_seen_names`` guards cyclic local rebinds (``fn = fn``,
        ``a = b; b = a``) — a cycle degrades to unresolved instead of
        recursing unboundedly."""
        if isinstance(expr, ast.Lambda):
            rec = self.records.get(id(expr))
            return [rec] if rec is not None else []
        out = self.resolve_call(sf, expr, enclosing, ctor_types)
        if out or not isinstance(expr, ast.Name) or enclosing is None:
            return out
        seen_names = set(_seen_names or ())
        if expr.id in seen_names:
            return []
        seen_names.add(expr.id)
        found: List[FuncRecord] = []
        for sub in body_walk(enclosing):
            exprs: List[ast.AST] = []
            if isinstance(sub, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in sub.targets):
                exprs = [sub.value]
            elif isinstance(sub, ast.For) and \
                    isinstance(sub.target, ast.Name) and \
                    sub.target.id == expr.id and \
                    isinstance(sub.iter, (ast.Tuple, ast.List)):
                exprs = list(sub.iter.elts)
            for e in exprs:
                if isinstance(e, (ast.Tuple, ast.List)):
                    exprs.extend(e.elts)
                    continue
                found.extend(self.resolve_callable(sf, e, enclosing,
                                                   ctor_types,
                                                   _seen_names=seen_names))
        seen: Set[int] = set()
        return [r for r in found
                if id(r.node) not in seen and not seen.add(id(r.node))]

    def _spawn_entry_expr(self, call: ast.Call, pos: int,
                          kwnames) -> Optional[ast.AST]:
        expr = call.args[pos] if len(call.args) > pos else None
        for kw in call.keywords:
            if kw.arg in kwnames:
                expr = kw.value
        return expr

    def is_thread_subclass(self, class_key: Tuple[str, str]) -> bool:
        return self._inherits(class_key, THREAD_BASES)

    def _inherits(self, class_key, dotted_bases) -> bool:
        seen = set()
        frontier = [class_key]
        while frontier:
            k = frontier.pop()
            if k in seen:
                continue
            seen.add(k)
            for b in self.class_bases.get(k, []):
                if b in dotted_bases:
                    return True
                bk = self._class_keys.get(b)
                if bk is not None:
                    frontier.append(bk)
        return False

    def spawn_sites(self) -> List[SpawnSite]:
        """Every concurrency entry point in scope (cached)."""
        if getattr(self, "_spawn_sites", None) is not None:
            return self._spawn_sites
        sites: List[SpawnSite] = []
        arg_ids: Set[int] = set()     # entry-expr node ids (spawn edges)
        for sf in self.files:
            idx = self.file_index[sf.path]
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    key = (sf.resolver.module, node.name)
                    if self.is_thread_subclass(key):
                        recs = self.method_records(key, "run",
                                                   include_subclasses=False)
                        recs = [r for r in recs if r.class_key == key]
                        if recs:
                            sites.append(SpawnSite(
                                sf, node, "thread-subclass",
                                f"{node.name}.run", recs))
                    elif self._inherits(key, HANDLER_BASES):
                        recs = [r for n in ("handle", "setup", "finish")
                                for r in self.method_records(
                                    key, n, include_subclasses=False)
                                if r.class_key == key]
                        if recs:
                            sites.append(SpawnSite(
                                sf, node, "handler",
                                f"{node.name}.handle", recs))
                    continue
                if not isinstance(node, ast.Call):
                    continue
                resolved = sf.resolver.resolve(node.func)
                kind = pos = kwnames = None
                if resolved in _SPAWN_CTORS:
                    kind, pos, kwnames = _SPAWN_CTORS[resolved]
                elif resolved in _SPAWN_REGISTRARS:
                    kind, pos, kwnames = _SPAWN_REGISTRARS[resolved]
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _SPAWN_METHODS:
                    kind, pos = _SPAWN_METHODS[node.func.attr]
                    kwnames = ("fn",)
                if kind is None:
                    continue
                expr = self._spawn_entry_expr(node, pos, kwnames)
                if expr is None:
                    continue
                hresolved = sf.resolver.resolve(expr)
                if kind == "signal" and hresolved in _NON_HANDLERS:
                    continue
                enc = idx.enclosing.get(id(expr))
                enc_rec = self.records.get(id(enc)) if enc is not None \
                    else None
                ctor_types = self._local_ctor_types(enc_rec) \
                    if enc_rec is not None else None
                entries = self.resolve_callable(sf, expr, enc, ctor_types)
                desc = ImportResolver.dotted(expr) or \
                    ("<lambda>" if isinstance(expr, ast.Lambda)
                     else ast.dump(expr)[:40])
                sites.append(SpawnSite(sf, node, kind, desc, entries))
                arg_ids.add(id(expr))
        self._spawn_arg_ids = arg_ids
        self._spawn_sites = sites
        return sites

    def _role_callees(self, rec: FuncRecord) -> List[FuncRecord]:
        """:meth:`callees` with the SPAWN EDGES cut: a callable handed to
        ``Thread(target=…)``/``signal.signal``/``submit`` runs on the new
        thread, not the spawning one, so it is not a same-role callee."""
        cache = getattr(self, "_role_callees_cache", None)
        if cache is None:
            cache = self._role_callees_cache = {}
        cached = cache.get(id(rec.node))
        if cached is not None:
            return cached
        self.spawn_sites()            # ensures _spawn_arg_ids
        skip = self._spawn_arg_ids
        idx = self.file_index[rec.sf.path]
        ctor_types = self._local_ctor_types(rec)
        out: List[FuncRecord] = []
        seen: Set[int] = set()

        def add(targets) -> None:
            for t in targets:
                if id(t.node) not in seen and t.node is not rec.node:
                    seen.add(id(t.node))
                    out.append(t)

        for sub in body_walk(rec.node):
            if isinstance(sub, ast.Call):
                enc = idx.enclosing.get(id(sub.func), rec.node)
                add(self.resolve_call(rec.sf, sub.func, enc, ctor_types,
                                      skip_generic_unique=True))
                for arg in list(sub.args) + [kw.value for kw in
                                             sub.keywords]:
                    if id(arg) in skip:
                        continue
                    if isinstance(arg, (ast.Name, ast.Attribute)):
                        enc = idx.enclosing.get(id(arg), rec.node)
                        add(self.resolve_call(rec.sf, arg, enc,
                                              ctor_types,
                                              skip_generic_unique=True))
        cache[id(rec.node)] = out
        return out

    def _role_closure(self, seeds: Iterable[FuncRecord]) -> List[FuncRecord]:
        out: List[FuncRecord] = []
        seen: Set[int] = set()
        frontier = list(seeds)
        while frontier:
            rec = frontier.pop()
            if id(rec.node) in seen:
                continue
            seen.add(id(rec.node))
            out.append(rec)
            frontier.extend(self._role_callees(rec))
        return out

    def thread_roles(self) -> List[ThreadRole]:
        """Concurrent roles (one per distinct entry set), cached.  Role
        names are ``<kind>:<entry qualname>`` — stable across runs."""
        if getattr(self, "_thread_roles", None) is not None:
            return self._thread_roles
        by_name: Dict[str, ThreadRole] = {}
        for site in self.spawn_sites():
            if site.entries:
                name = f"{site.kind}:{site.entries[0].qualname}"
            else:
                name = f"{site.kind}:{site.sf.path}:{site.line}"
            role = by_name.get(name)
            if role is None:
                role = by_name[name] = ThreadRole(name, site.kind)
            role.sites.append(site)
            known = {id(e.node) for e in role.entries}
            role.entries.extend(e for e in site.entries
                                if id(e.node) not in known)
        self._thread_roles = [by_name[n] for n in sorted(by_name)]
        return self._thread_roles

    def role_members(self, role: ThreadRole) -> List[FuncRecord]:
        return self._role_closure(role.entries)

    def role_map(self) -> Dict[int, Set[str]]:
        """func-node id -> the set of role names the function can run
        under.

        The ``main`` role's seeds are the records with NO incoming
        call-graph reference (the public surface: CLI mains, class
        methods called through duck-typed receivers, constructors) minus
        the concurrent entries; its members are their closure.  A helper
        referenced ONLY by a thread entry's closure therefore stays out
        of ``main`` — attributing it to the spawning thread too would
        make every thread-private helper read as cross-thread.  The
        approximation is deliberately biased toward fewer false
        conflicts: an unresolvable duck-typed call from main into a
        role-private helper is missed, never invented."""
        cached = getattr(self, "_role_map", None)
        if cached is not None:
            return cached
        roles = self.thread_roles()
        out: Dict[int, Set[str]] = {}
        entry_ids: Set[int] = set()
        for role in roles:
            for rec in self.role_members(role):
                out.setdefault(id(rec.node), set()).add(role.name)
            entry_ids.update(id(e.node) for e in role.entries)
        referenced: Set[int] = set()
        for rec in self.records.values():
            for callee in self._role_callees(rec):
                referenced.add(id(callee.node))
        main_seeds = []
        for rec in self.records.values():
            if isinstance(rec.node, ast.Lambda):
                continue
            if id(rec.node) in entry_ids or id(rec.node) in referenced:
                continue
            fidx = self.file_index[rec.sf.path]
            if fidx.parent_func.get(id(rec.node)) is not None:
                continue              # nested defs: reachable via parent
            main_seeds.append(rec)
        for rec in self._role_closure(main_seeds):
            out.setdefault(id(rec.node), set()).add(MAIN_ROLE)
        for rec in self.records.values():
            out.setdefault(id(rec.node), {MAIN_ROLE})
        self._role_map = out
        return out

    def roles_of(self, rec: FuncRecord) -> Set[str]:
        return self.role_map().get(id(rec.node), {MAIN_ROLE})

    # -- class attribute construction map (lock/sync-object identity) ------

    def class_attr_ctors(self, class_key: Tuple[str, str]) -> Dict[str, str]:
        """``self.X = <Call>`` assignments anywhere in the class (its own
        methods): attr -> the resolved constructor's dotted path (or the
        in-scope class path).  The concurrency checkers use it to know a
        ``_lock`` is a ``threading.Lock`` vs ``RLock``, a ``_q`` is a
        ``queue.Queue``, and which class ``self.center`` is."""
        cache = getattr(self, "_attr_ctor_cache", None)
        if cache is None:
            cache = self._attr_ctor_cache = {}
        cached = cache.get(class_key)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        module, _cls_name = class_key
        for name, recs in self.methods.items():
            for rec in recs:
                if rec.class_key != class_key:
                    continue
                for sub in body_walk(rec.node):
                    if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        continue
                    value = sub.value
                    if not isinstance(value, ast.Call):
                        continue
                    resolved = rec.sf.resolver.resolve(value.func)
                    if resolved is None and \
                            isinstance(value.func, ast.Name):
                        fidx = self.file_index[rec.sf.path]
                        if value.func.id in fidx.classes:
                            resolved = f"{module}.{value.func.id}"
                    if resolved is None:
                        continue
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            out.setdefault(t.attr, resolved)
        cache[class_key] = out
        return out


# ---------------------------------------------------------------------------
# small shared AST helpers
# ---------------------------------------------------------------------------

def body_walk(fn: ast.AST):
    """Walk a function's body, NOT descending into nested FunctionDefs
    (reachable separately when called) but following inline lambdas
    (they run at trace time via tree.map etc.)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _FuncDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def consumed_key_name(call: ast.Call, resolver: ImportResolver
                      ) -> Optional[str]:
    """The Name consumed as the key of a ``jax.random.<sampler>`` call,
    or None."""
    resolved = resolver.resolve(call.func)
    if not resolved or not resolved.startswith("jax.random."):
        return None
    if resolved.rsplit(".", 1)[-1] not in KEY_CONSUMERS:
        return None
    key_arg = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "key":
            key_arg = kw.value
    if isinstance(key_arg, ast.Name):
        return key_arg.id
    return None


def axis_values(call: ast.Call, cname: str, resolver: ImportResolver,
                index: Optional[ProgramIndex] = None,
                local_consts: Optional[Dict[str, object]] = None
                ) -> Tuple:
    """Statically-known axis names of one collective call: a tuple of
    strings for every axis entry that resolves to a literal, or () when
    the axis argument is not statically evaluable (parameters, computed
    tuples) — unknown axes are SKIPPED, never guessed."""
    pos = COLLECTIVES[cname]
    arg = call.args[pos] if len(call.args) > pos else None
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis_names"):
            arg = kw.value
    if arg is None:
        return ()
    vals = _eval_axis(arg, resolver, index, local_consts)
    return tuple(vals) if vals is not None else ()


def _eval_axis(node: ast.AST, resolver: ImportResolver,
               index: Optional[ProgramIndex],
               local_consts: Optional[Dict[str, object]]
               ) -> Optional[List[str]]:
    if isinstance(node, ast.Constant):
        return [node.value] if isinstance(node.value, str) else None
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in node.elts:
            sub = _eval_axis(e, resolver, index, local_consts)
            if sub is None:
                return None          # partially-unknown tuple: skip all
            out.extend(sub)
        return out
    if isinstance(node, ast.Name) and local_consts is not None and \
            node.id in local_consts:
        v = local_consts[node.id]
        return [v] if isinstance(v, str) else None
    if isinstance(node, (ast.Name, ast.Attribute)):
        resolved = resolver.resolve(node)
        if resolved and index is not None:
            v = index.module_constant(resolved)
            if isinstance(v, str):
                return [v]
        return None
    return None
