"""tpulint CLI — the one analysis entry point ``scripts/lint.py`` execs.

Modes:

* default: run the suite, print findings; non-baselined findings fail
  (exit 1), stale baseline entries only warn.
* ``--check-baseline`` (the tier-1 gate): ALSO fail on stale entries —
  the committed baseline must be exact (no drift in either direction).
* ``--update-baseline``: regenerate ``tpulint_baseline.json``
  deterministically (sorted, path-relative), preserving justifications
  of retained entries; new entries get ``TODO: justify``.
* ``--json``: machine-readable findings + baseline delta.
* ``--only`` / ``--disable``: comma-separated checker names;
  ``--list-checks`` prints the registry.

Exit codes: 0 clean, 1 findings/drift, 2 bad invocation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import checkers as _checkers  # noqa: F401  (registers the suite)
from .core import (BASELINE_NAME, CHECKERS, compare_baseline, load_baseline,
                   run_lint, save_baseline)


def _repo_root() -> str:
    # core.py lives at <root>/theanompi_tpu/analysis/cli.py
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _split(value: Optional[str]) -> Optional[List[str]]:
    if value is None:
        return None
    return [v.strip() for v in value.split(",") if v.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint.py",
        description="tpulint — AST invariant checkers (docs/design.md §12)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the repo set)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: inferred from this file)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--only", default=None,
                    help="comma-separated checker names to run")
    ap.add_argument("--disable", default=None,
                    help="comma-separated checker names to skip")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--check-baseline", action="store_true",
                    help="fail on stale baseline entries too (tier-1 mode)")
    ap.add_argument("--list-checks", action="store_true")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    if args.list_checks:
        for name in sorted(CHECKERS):
            print(f"{name}: {CHECKERS[name].description}")
        return 0

    root = os.path.abspath(args.root or _repo_root())
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    # a typo'd explicit path must not read as "linted clean" — the
    # default set is allowed to have absent members (bare roots), an
    # explicitly named one is not
    missing = [p for p in args.paths
               if not os.path.exists(os.path.join(root, p))]
    if missing:
        print(f"lint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    try:
        findings = run_lint(root, paths=args.paths or None,
                            only=_split(args.only),
                            disable=_split(args.disable))
    except KeyError as e:
        print(f"lint: {e.args[0]}", file=sys.stderr)
        return 2

    entries = load_baseline(baseline_path)
    # a partial run (explicit paths / --only) must not call untouched
    # baseline entries stale — staleness is only meaningful repo-wide
    partial = bool(args.paths or args.only or args.disable)
    new, matched, stale = compare_baseline(findings, entries)
    if partial:
        stale = []

    if args.update_baseline:
        if partial:
            # a partial run only sees a slice of the findings — writing
            # it out would silently drop every entry outside the slice
            print("lint: --update-baseline requires a full run (no "
                  "paths/--only/--disable)", file=sys.stderr)
            return 2
        saved = save_baseline(baseline_path, findings, entries)
        print(f"tpulint: baseline written to "
              f"{os.path.relpath(baseline_path, root)} "
              f"({len(saved)} entries)")
        todo = sum(1 for e in saved
                   if e["justification"].startswith("TODO"))
        if todo:
            print(f"tpulint: {todo} entries need a justification "
                  "(edit the file)", file=sys.stderr)
        return 0

    # the documented baseline contract: entries carry a real one-line
    # justification; TODO placeholders nag on EVERY run, not just the
    # --update-baseline that wrote them
    todo = [e for e in entries
            if str(e.get("justification", "")).startswith("TODO")]
    if todo:
        # stderr, so --json stdout stays machine-readable
        for e in todo:
            print(f"baseline entry needs a justification: "
                  f"{e.get('check')}: {e.get('path')}: "
                  f"{e.get('message')}", file=sys.stderr)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in new],
            "baselined": len(matched),
            "stale_baseline": stale,
        }, indent=2, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"stale baseline entry: {e.get('check')}: "
                  f"{e.get('path')}: {e.get('message')}", file=sys.stderr)
        status = (f"tpulint: {len(findings)} finding(s) — {len(new)} new, "
                  f"{len(matched)} baselined, {len(stale)} stale baseline "
                  "entr(ies)")
        print(status)

    if new:
        return 1
    if stale and args.check_baseline:
        return 1
    return 0
