"""tpulint CLI — the one analysis entry point ``scripts/lint.py`` execs.

Modes:

* default: run the suite, print findings; non-baselined findings fail
  (exit 1), stale baseline entries only warn.
* ``--check-baseline`` (the tier-1 gate): ALSO fail on stale entries —
  the committed baseline must be exact (no drift in either direction).
* ``--update-baseline``: regenerate ``tpulint_baseline.json``
  deterministically (sorted, path-relative), preserving justifications
  of retained entries; new entries get ``TODO: justify``.
* ``--format json`` (``--json`` kept as an alias): machine-readable
  findings + baseline delta; every finding carries a stable
  ``fingerprint`` (schema in docs/design.md §12).
* ``--format sarif``: SARIF 2.1.0 log of the NEW findings (stable
  fingerprints → ``partialFingerprints``) for CI diff annotation;
  ``precommit_lint.sh`` writes one when ``TPULINT_SARIF`` is set.
* ``--only`` / ``--disable``: comma-separated checker names;
  ``--list-checks`` prints the registry.
* ``--diff <ref>``: lint only the ``.py`` files changed vs a git ref
  (``CACHED`` = the staged index vs HEAD — the precommit hook's mode),
  filtered to the repo lint scope.  Partial-run semantics (stale
  baseline entries are not judged, ``--update-baseline`` refuses) and
  the per-file result cache apply, so CI and precommit runs on big
  trees stay sub-second.  Untracked files are invisible to a git diff
  — a full run still covers them.
* ``--no-cache``: bypass the ``.tpulint_cache/`` result cache (on by
  default; keyed on content hashes + the analysis-source fingerprint,
  so it can only ever hit on a byte-identical configuration —
  ``analysis/cache.py``).
* ``--verbose``: list every TODO-justified baseline entry instead of
  the one-line summary.

Exit codes: 0 clean, 1 findings/drift, 2 bad invocation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from . import cache as cache_mod
from . import checkers as _checkers  # noqa: F401  (registers the suite)
from .core import (BASELINE_NAME, CHECKERS, DEFAULT_PATHS, Finding,
                   compare_baseline, file_scoped_checkers, iter_py_paths,
                   load_baseline, run_lint, save_baseline)


def _repo_root() -> str:
    # core.py lives at <root>/theanompi_tpu/analysis/cli.py
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _split(value: Optional[str]) -> Optional[List[str]]:
    """Comma-split + group-alias expansion (``--only concurrency`` →
    the four host-concurrency checkers), deduplicated in order."""
    if value is None:
        return None
    from .checkers import CHECK_GROUPS
    out: List[str] = []
    for v in value.split(","):
        v = v.strip()
        if not v:
            continue
        for name in CHECK_GROUPS.get(v, (v,)):
            if name not in out:
                out.append(name)
    return out


def _lint_scope(path: str) -> bool:
    """Is a repo-relative path inside the default lint scope?"""
    for d in DEFAULT_PATHS:
        if path == d or path.startswith(d.rstrip("/") + "/"):
            return True
    return False


def _git_changed(root: str, ref: str):
    """Repo-relative ``.py`` paths changed vs ``ref`` (``CACHED`` = the
    staged index vs HEAD), deletions excluded.  Git runs in ``root``
    when it is a repository, else in the cwd — the precommit hook lints
    a temp checkout of the index (no ``.git``) from the repo root, so
    the diff is computed against the real repository either way.
    Returns ``(paths, None)`` or ``(None, error message)``."""
    import subprocess
    # .git is a DIRECTORY in a primary checkout but a FILE in worktrees
    # and submodules — exists() covers all three; a non-repo root (the
    # precommit hook's temp index checkout) falls back to the cwd
    git_root = root if os.path.exists(os.path.join(root, ".git")) \
        else os.getcwd()
    cmd = ["git", "-C", git_root, "diff", "--name-only",
           "--diff-filter=d"]
    cmd.append("--cached" if ref == "CACHED" else ref)
    cmd += ["--", "*.py"]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=60)
    except (OSError, subprocess.TimeoutExpired) as e:
        return None, f"git unavailable ({e!r})"
    if out.returncode != 0:
        return None, (out.stderr.strip().splitlines() or
                      [f"git diff exited {out.returncode}"])[-1]
    return [ln.strip().replace(os.sep, "/")
            for ln in out.stdout.splitlines() if ln.strip()], None


def _cached_run(root, paths, only, disable, cache_dir=None):
    """Run the suite through the result cache.  Returns
    ``(findings, status)`` with status in hit/miss/off (off = the cache
    store is unusable)."""
    unknown = [n for n in (list(only or []) + list(disable or []))
               if n not in CHECKERS]
    if unknown:
        raise KeyError(f"unknown checker(s) {unknown}; have "
                       f"{sorted(CHECKERS)}")
    selected = sorted(n for n in CHECKERS
                      if (only is None or n in only)
                      and (disable is None or n not in disable))
    rels = iter_py_paths(root, paths)
    lint_rels = {r.replace(os.sep, "/") for r in rels}
    # EVERY file a disk-scoped checker loads beyond the lint selection
    # (live-probe targets, the key_extra vocabulary, ops/ kernels) must
    # key the cache even on partial runs whose path set does not cover
    # it — but they are NOT part of the linted set then, so no per-file
    # entry may be stored for them (it would read as "no findings" to a
    # later full run).  Omitting one would let a stale tree hit mask a
    # drift the checker exists to catch.  Checkers declare the set via
    # ``Checker.disk_scoped`` (paths or glob patterns).
    disk_extra: List[str] = []
    for name in selected:
        for pat in CHECKERS[name].disk_scoped:
            if any(ch in pat for ch in "*?["):
                import glob as _glob
                probes = sorted(
                    os.path.relpath(m, root).replace(os.sep, "/")
                    for m in _glob.glob(os.path.join(root, pat))
                    if m.endswith(".py"))
            else:
                probes = [pat]
            for probe in probes:
                if probe not in lint_rels and probe not in disk_extra \
                        and os.path.exists(os.path.join(root, probe)):
                    disk_extra.append(probe)
    if disk_extra:
        rels = list(rels) + disk_extra
    hashes = cache_mod.file_hashes(root, rels)
    afp = cache_mod.analysis_fingerprint()
    store = cache_mod.LintCache(root, cache_dir)
    tkey = cache_mod.tree_key(afp, selected, list(paths or []), hashes)
    cached = store.load_tree(tkey)
    if cached is not None:
        return cached, "hit"

    # tree miss: splice per-file hits for the file-scoped checkers and
    # run everything else live
    fsc = [n for n in file_scoped_checkers() if n in selected]
    fkeys = {rel: cache_mod.file_key(afp, fsc, sha)
             for rel, sha in hashes}
    file_cache: Dict[str, List[Finding]] = {}
    for rel, key in fkeys.items():
        hit = store.load_file(key)
        if hit is not None:
            file_cache[rel] = hit
    findings = run_lint(root, paths=paths, only=only, disable=disable,
                        file_cache=file_cache or None)
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        if f.check in fsc:
            by_path.setdefault(f.path, []).append(f)
    for rel, key in fkeys.items():
        if rel not in file_cache and rel in lint_rels:
            store.store_file(key, by_path.get(rel, []))
    store.store_tree(tkey, findings)
    return findings, "miss"


def _sarif_log(new: List[Finding]) -> dict:
    """Minimal SARIF 2.1.0 log over the NEW findings (the baseline is
    tpulint's own suppression layer — CI annotates what would fail the
    gate).  ``ruleId`` is the checker name; ``partialFingerprints``
    carries each finding's stable id so SARIF consumers track a finding
    across runs the way the baseline does."""
    rule_ids = sorted({f.check for f in new})
    rules = [{
        "id": rid,
        "shortDescription": {
            "text": CHECKERS[rid].description if rid in CHECKERS
            else rid},
    } for rid in rule_ids]
    results = [{
        "ruleId": f.check,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": max(f.line, 1),
                           "startColumn": max(f.col + 1, 1)},
            },
        }],
        "partialFingerprints": {"tpulintFingerprint/v1": f.stable_id},
    } for f in new]
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "informationUri": "docs/design.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint.py",
        description="tpulint — whole-program invariant checkers "
                    "(docs/design.md §12)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the repo set)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: inferred from this file)")
    ap.add_argument("--format", default=None, dest="fmt",
                    choices=("human", "json", "sarif"),
                    help="output format (default: human; sarif emits "
                         "a SARIF 2.1.0 log of the NEW findings for "
                         "CI diff annotation)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="alias for --format json")
    ap.add_argument("--only", default=None,
                    help="comma-separated checker names (or the "
                         "'concurrency' group) to run")
    ap.add_argument("--disable", default=None,
                    help="comma-separated checker names (or group) "
                         "to skip")
    ap.add_argument("--diff", default=None, metavar="REF",
                    help="lint only .py files changed vs the git ref "
                         "(CACHED = staged index vs HEAD); partial-run "
                         "semantics")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--update-baseline", action="store_true")
    ap.add_argument("--check-baseline", action="store_true",
                    help="fail on stale baseline entries too (tier-1 mode)")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the .tpulint_cache/ result cache")
    ap.add_argument("--cache-dir", default=None,
                    help="cache directory (default: <root>/"
                         ".tpulint_cache; the precommit hook points "
                         "this at the repo while rooting at a temp "
                         "index checkout)")
    ap.add_argument("--verbose", action="store_true",
                    help="list every TODO-justified baseline entry")
    ap.add_argument("--list-checks", action="store_true")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    if args.list_checks:
        for name in sorted(CHECKERS):
            print(f"{name}: {CHECKERS[name].description}")
        return 0

    as_json = args.as_json or args.fmt == "json"
    root = os.path.abspath(args.root or _repo_root())
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    if args.diff:
        if args.paths:
            print("lint: --diff and explicit paths are mutually "
                  "exclusive", file=sys.stderr)
            return 2
        if args.update_baseline:
            # refused HERE, not only at the shared partial-run check
            # below: an empty changeset's early exit 0 must not read as
            # "baseline updated" to automation
            print("lint: --update-baseline requires a full run (no "
                  "paths/--diff/--only/--disable)", file=sys.stderr)
            return 2
        changed, err = _git_changed(root, args.diff)
        if changed is None:
            print(f"lint: --diff {args.diff}: {err}", file=sys.stderr)
            return 2
        # scope-filter, and drop paths absent from THIS root (a
        # restricted precommit checkout holds only the staged blobs)
        args.paths = sorted({
            p for p in changed
            if p.endswith(".py") and _lint_scope(p)
            and os.path.exists(os.path.join(root, p))})
        if not args.paths:
            print(f"lint: no changed python files in lint scope vs "
                  f"{args.diff}")
            return 0
    # a typo'd explicit path must not read as "linted clean" — the
    # default set is allowed to have absent members (bare roots), an
    # explicitly named one is not
    missing = [p for p in args.paths
               if not os.path.exists(os.path.join(root, p))]
    if missing:
        print(f"lint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    try:
        if args.no_cache:
            findings = run_lint(root, paths=args.paths or None,
                                only=_split(args.only),
                                disable=_split(args.disable))
            cache_status = "off"
        else:
            findings, cache_status = _cached_run(
                root, args.paths or None, _split(args.only),
                _split(args.disable), cache_dir=args.cache_dir)
    except KeyError as e:
        print(f"lint: {e.args[0]}", file=sys.stderr)
        return 2

    entries = load_baseline(baseline_path)
    # a partial run (explicit paths / --only) must not call untouched
    # baseline entries stale — staleness is only meaningful repo-wide
    partial = bool(args.paths or args.only or args.disable)
    new, matched, stale = compare_baseline(findings, entries)
    if partial:
        stale = []

    if args.update_baseline:
        if partial:
            # a partial run only sees a slice of the findings — writing
            # it out would silently drop every entry outside the slice
            print("lint: --update-baseline requires a full run (no "
                  "paths/--diff/--only/--disable)", file=sys.stderr)
            return 2
        saved = save_baseline(baseline_path, findings, entries)
        print(f"tpulint: baseline written to "
              f"{os.path.relpath(baseline_path, root)} "
              f"({len(saved)} entries)")
        todo = sum(1 for e in saved
                   if e["justification"].startswith("TODO"))
        if todo:
            print(f"tpulint: {todo} entries need a justification "
                  "(edit the file)", file=sys.stderr)
        return 0

    # the documented baseline contract: entries carry a real one-line
    # justification; TODO placeholders nag on EVERY run, not just the
    # --update-baseline that wrote them — but as ONE summary line, not
    # a per-entry flood (--verbose restores the full list)
    todo = [e for e in entries
            if str(e.get("justification", "")).startswith("TODO")]
    if todo:
        # stderr, so json stdout stays machine-readable
        if args.verbose:
            for e in todo:
                print(f"baseline entry needs a justification: "
                      f"{e.get('check')}: {e.get('path')}: "
                      f"{e.get('message')}", file=sys.stderr)
        else:
            n = len(todo)
            print(f"tpulint: {n} baseline entr{'y' if n == 1 else 'ies'} "
                  "with a TODO placeholder — each needs a justification "
                  "(--verbose lists them)", file=sys.stderr)

    if args.fmt == "sarif":
        print(json.dumps(_sarif_log(new), indent=2, sort_keys=True))
    elif as_json:
        def enrich(f: Finding) -> dict:
            d = f.to_dict()
            d["fingerprint"] = f.stable_id
            return d

        print(json.dumps({
            "version": 2,
            "findings": [enrich(f) for f in findings],
            "new": [enrich(f) for f in new],
            "baselined": len(matched),
            "stale_baseline": stale,
            "cache": cache_status,
        }, indent=2, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"stale baseline entry: {e.get('check')}: "
                  f"{e.get('path')}: {e.get('message')}", file=sys.stderr)
        status = (f"tpulint: {len(findings)} finding(s) — {len(new)} new, "
                  f"{len(matched)} baselined, {len(stale)} stale baseline "
                  f"entr(ies) [cache {cache_status}]")
        print(status)

    if new:
        return 1
    if stale and args.check_baseline:
        return 1
    return 0
