// Native data-loader hot path.
//
// TPU-native rebuild of the runtime-native part of Theano-MPI's parallel
// loader (reference: theanompi/models/data/ loader child process +
// lib/exchanger_strategy.py PyCUDA kernels — SURVEY.md §2.8, §2.9 N3):
// the reference spawned a child process that loaded a .hkl batch, ran
// crop/mirror/mean-subtract augmentation on CPU, and wrote the float32
// result into the trainer's GPU buffer over a CUDA IPC handle.  On TPU the
// IPC trick is ordinary async host→device transfer, but the CPU
// augmentation itself is still the host-side hot loop: at AlexNet rates a
// 128-image batch means ~25M uint8 reads → ~79MB of float32 writes per
// step per worker.  NumPy does this single-threaded with intermediate
// copies; this library does it in one fused multithreaded pass.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this
// environment).  Output is always NHWC float32 (TPU conv layout); input may
// be NHWC or NCHW ("bc01", the reference's batch-file layout) — the
// transpose fuses into the same pass.
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread loader.cc -o _loader.so
// (driven by theanompi_tpu/native/__init__.py, cached by mtime).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct AugmentArgs {
  const uint8_t* in;   // [n,h,w,c] or [n,c,h,w]
  float* out;          // [n,crop,crop,c]
  int n, h, w, c, crop;
  int in_nchw;         // input layout: 0 = NHWC, 1 = NCHW
  const int* oy;       // per-image crop offsets [n]
  const int* ox;       // [n]
  const uint8_t* flip; // per-image horizontal mirror [n]
  const float* mean;   // nullptr, or [crop,crop,c] (pre-cropped mean image)
  float mean_scalar;   // used when mean == nullptr
};

// One image: fused crop + mirror + mean-subtract + cast (+ transpose).
void augment_one(const AugmentArgs& a, int i) {
  const int h = a.h, w = a.w, c = a.c, crop = a.crop;
  const int oy = a.oy[i], ox = a.ox[i];
  const bool flip = a.flip[i] != 0;
  float* dst = a.out + (size_t)i * crop * crop * c;

  if (!a.in_nchw) {
    const uint8_t* src = a.in + (size_t)i * h * w * c;
    for (int y = 0; y < crop; ++y) {
      const uint8_t* row = src + ((size_t)(y + oy) * w + ox) * c;
      float* drow = dst + (size_t)y * crop * c;
      const float* mrow = a.mean ? a.mean + (size_t)y * crop * c : nullptr;
      if (!flip) {
        if (mrow) {
          for (int x = 0; x < crop * c; ++x) drow[x] = (float)row[x] - mrow[x];
        } else {
          const float m = a.mean_scalar;
          for (int x = 0; x < crop * c; ++x) drow[x] = (float)row[x] - m;
        }
      } else {
        // mirror: output x reads input (crop-1-x); mean indexed by OUTPUT x
        for (int x = 0; x < crop; ++x) {
          const uint8_t* px = row + (size_t)(crop - 1 - x) * c;
          float* dpx = drow + (size_t)x * c;
          if (mrow) {
            const float* mpx = mrow + (size_t)x * c;
            for (int k = 0; k < c; ++k) dpx[k] = (float)px[k] - mpx[k];
          } else {
            for (int k = 0; k < c; ++k) dpx[k] = (float)px[k] - a.mean_scalar;
          }
        }
      }
    }
  } else {
    // NCHW input: gather channel planes, write NHWC.
    const uint8_t* src = a.in + (size_t)i * c * h * w;
    for (int y = 0; y < crop; ++y) {
      float* drow = dst + (size_t)y * crop * c;
      const float* mrow = a.mean ? a.mean + (size_t)y * crop * c : nullptr;
      for (int x = 0; x < crop; ++x) {
        const int sx = flip ? (ox + crop - 1 - x) : (ox + x);
        const size_t plane_off = (size_t)(y + oy) * w + sx;
        float* dpx = drow + (size_t)x * c;
        for (int k = 0; k < c; ++k) {
          const float m = mrow ? mrow[(size_t)x * c + k] : a.mean_scalar;
          dpx[k] = (float)src[(size_t)k * h * w + plane_off] - m;
        }
      }
    }
  }
}

void run_range(const AugmentArgs& a, int lo, int hi) {
  for (int i = lo; i < hi; ++i) augment_one(a, i);
}

}  // namespace

extern "C" {

// Fused batch augmentation.  in: uint8 [n,h,w,c] (in_nchw=0) or [n,c,h,w]
// (in_nchw=1); out: float32 [n,crop,crop,c]; oy/ox/flip: per-image params
// [n]; mean: nullptr (use mean_scalar) or float32 [crop,crop,c] already
// cropped to the output window.  n_threads<=1 runs inline.
void tmpi_augment_u8(const uint8_t* in, float* out, int n, int h, int w,
                     int c, int crop, int in_nchw, const int* oy,
                     const int* ox, const uint8_t* flip, const float* mean,
                     float mean_scalar, int n_threads) {
  AugmentArgs a{in, out, n, h, w, c, crop, in_nchw, oy, ox, flip,
                mean, mean_scalar};
  if (n_threads <= 1 || n <= 1) {
    run_range(a, 0, n);
    return;
  }
  if (n_threads > n) n_threads = n;
  std::vector<std::thread> ts;
  ts.reserve(n_threads);
  const int per = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    const int lo = t * per;
    const int hi = lo + per < n ? lo + per : n;
    if (lo >= hi) break;
    ts.emplace_back([&a, lo, hi] { run_range(a, lo, hi); });
  }
  for (auto& t : ts) t.join();
}

// Version stamp so the Python side can cache-bust compiled objects.
int tmpi_loader_abi_version() { return 1; }

}  // extern "C"
