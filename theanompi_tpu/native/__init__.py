"""Native runtime components (C++ via ctypes).

The reference's runtime leaned on native code in two places of its own
(SURVEY.md §2.9): runtime-compiled PyCUDA kernels in the exchanger (on TPU
those became Pallas kernels — ``theanompi_tpu/ops/compress.py``) and the
parallel-loader child process that augmented batches on CPU and pushed them
into the GPU over CUDA IPC (§2.8).  The CPU half of that loader — the fused
crop/mirror/mean-subtract/cast pass — is this module: ``loader.cc`` compiled
at first use with the system ``g++`` (mirroring the reference's
compile-on-first-run PyCUDA habit) and called through ctypes.  No pybind11 in
this environment; the C ABI + ctypes keeps the binding dependency-free.

``augment_batch`` is the public entry; it transparently falls back to a
NumPy implementation when no compiler is available, and both paths are
bit-identical (tested in ``tests/test_native_loader.py``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "loader.cc")
_SO = os.path.join(_HERE, "_loader.so")

_lock = threading.Lock()
_lib = None
_lib_tried = False

DEFAULT_THREADS = min(16, os.cpu_count() or 1)


def _build() -> Optional[str]:
    """Compile loader.cc → _loader.so if stale/absent. Returns path or None.

    Compiles to a per-process temp name and installs with an atomic
    ``os.replace`` so concurrent first-use across processes (pytest-xdist, a
    multi-process host) can't interleave writes into one file — worst case
    both compile and the last install wins, both valid.
    """
    tmp = f"{_SO}.{os.getpid()}.tmp"
    try:
        if (os.path.exists(_SO)
                and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
            return _SO
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
               _SRC, "-o", tmp]
        # -march=native when the toolchain supports it (best-effort)
        probe = subprocess.run(cmd[:1] + ["-march=native", "-E", "-x", "c++",
                                          "-", "-o", os.devnull],
                               input=b"", capture_output=True)
        if probe.returncode == 0:
            cmd.insert(1, "-march=native")
        r = subprocess.run(cmd, capture_output=True)
        if r.returncode != 0:
            return None
        os.replace(tmp, _SO)
        return _SO
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def get_lib():
    """The loaded native library, or None (then callers use the NumPy path).
    Set ``TMPI_NO_NATIVE=1`` to force the fallback."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    with _lock:
        if _lib_tried:
            return _lib
        if os.environ.get("TMPI_NO_NATIVE"):
            _lib_tried = True
            return None
        so = _build()
        if so is not None:
            try:
                lib = ctypes.CDLL(so)
                lib.tmpi_augment_u8.restype = None
                lib.tmpi_augment_u8.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p,          # in, out
                    ctypes.c_int, ctypes.c_int, ctypes.c_int,  # n, h, w
                    ctypes.c_int, ctypes.c_int, ctypes.c_int,  # c, crop, nchw
                    ctypes.c_void_p, ctypes.c_void_p,          # oy, ox
                    ctypes.c_void_p, ctypes.c_void_p,          # flip, mean
                    ctypes.c_float, ctypes.c_int,              # mean_scalar, threads
                ]
                lib.tmpi_loader_abi_version.restype = ctypes.c_int
                assert lib.tmpi_loader_abi_version() == 1
                _lib = lib
            except (OSError, AssertionError):
                _lib = None
                try:            # don't let a corrupt .so poison future runs
                    os.remove(so)
                except OSError:
                    pass
        _lib_tried = True
        return _lib


def native_available() -> bool:
    return get_lib() is not None


def is_nchw(x: np.ndarray) -> bool:
    """Layout heuristic for 4-D image batches, shared by the native and
    NumPy augment paths and the .hkl readers: channels-first iff dim 1 looks
    like a channel count and the trailing dim doesn't."""
    return x.ndim == 4 and x.shape[1] in (1, 3) and x.shape[-1] not in (1, 3)


def _augment_numpy(x, oy, ox, flip, crop, mean, mean_scalar):
    n = x.shape[0]
    if is_nchw(x):
        x = x.transpose(0, 2, 3, 1)
    c = x.shape[-1]
    out = np.empty((n, crop, crop, c), np.float32)
    for i in range(n):
        win = x[i, oy[i]:oy[i] + crop, ox[i]:ox[i] + crop, :]
        if flip[i]:
            win = win[:, ::-1, :]
        out[i] = win
    out -= mean if mean is not None else np.float32(mean_scalar)
    return out


def augment_batch(x: np.ndarray, oy, ox, flip, crop: int,
                  mean: Optional[np.ndarray] = None,
                  mean_scalar: float = 0.0,
                  n_threads: Optional[int] = None) -> np.ndarray:
    """Fused crop+mirror+mean-subtract+cast: uint8 batch → float32 NHWC.

    ``x``: uint8 ``[n,h,w,c]`` (NHWC) or ``[n,c,h,w]`` (NCHW — the
    reference's bc01 batch files); ``oy``/``ox``/``flip``: per-image crop
    offsets and mirror flags (scalars broadcast); ``mean``: optional float32
    ``[crop,crop,c]`` pre-cropped mean image, else ``mean_scalar``.
    """
    assert x.dtype == np.uint8 and x.ndim == 4, (x.dtype, x.shape)
    n = x.shape[0]
    oy = np.broadcast_to(np.asarray(oy, np.int32), (n,))
    ox = np.broadcast_to(np.asarray(ox, np.int32), (n,))
    flip = np.broadcast_to(np.asarray(flip, np.uint8), (n,))
    nchw = is_nchw(x)
    c = x.shape[1] if nchw else x.shape[-1]
    if mean is not None:
        mean = np.ascontiguousarray(mean, np.float32)
        assert mean.shape == (crop, crop, c), (mean.shape, (crop, crop, c))

    lib = get_lib()
    if lib is None:
        return _augment_numpy(x, oy, ox, flip, crop, mean, mean_scalar)

    h, w = (x.shape[2], x.shape[3]) if nchw else (x.shape[1], x.shape[2])
    x = np.ascontiguousarray(x)
    oy = np.ascontiguousarray(oy)
    ox = np.ascontiguousarray(ox)
    flip = np.ascontiguousarray(flip)
    out = np.empty((n, crop, crop, c), np.float32)
    lib.tmpi_augment_u8(
        x.ctypes.data, out.ctypes.data, n, h, w, c, crop, int(nchw),
        oy.ctypes.data, ox.ctypes.data, flip.ctypes.data,
        mean.ctypes.data if mean is not None else None,
        ctypes.c_float(mean_scalar),
        n_threads if n_threads is not None else DEFAULT_THREADS)
    return out
