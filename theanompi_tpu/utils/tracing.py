"""Cross-process causal tracing: span context over the telemetry stream.

The telemetry plane (``utils/telemetry.py``) is rich but siloed per
process: each rank streams its own phases/gauges/anomalies, yet nothing
connects a worker's ``push_pull`` to the center handler that served it —
"this round was slow" cannot be split into compute vs wire vs center
queueing vs center apply.  This module adds the causal layer
(docs/design.md §17):

* **Trace/span context** — a ``trace_id`` minted per exchange round on
  the worker, with one span per unit of work (``round`` on the island,
  ``wire.<op>`` per RPC, ``center.<op>`` on the server).  Context rides
  the wire in an optional ``trace`` request-header field
  (``parallel/wire.py``, protocol v2: ``{"t": trace_id, "s": span_id}``;
  absent ⇒ pre-trace behavior), so retries and chaos-proxy duplicates
  carry the SAME ids and the server's spans join the client's.
* **Span events** — every finished span is one ``span`` event in the
  per-rank telemetry JSONL (``SPAN_EVENT`` schema below).  The server
  splits its time into ``q`` (center-lock queue wait — the center is the
  serialization point, so lock wait IS the queue) and ``a`` (apply under
  the lock), returned in the reply header so the client can decompose
  its observed RTT even with tracing disabled (the ``wire.server_queue``
  / ``wire.server_apply`` histograms).  A deduplicated twin (retry or
  chaos-proxy duplicate of a push that already landed) is tagged
  ``dedup`` and never double-counts on the critical path.
* **Assembly** — ``scripts/telemetry_report.py`` joins client and server
  spans across rank files by span id into per-round distributed traces,
  computes each round's critical path (compute | stage | wire | queue |
  apply), renders flow arrows between rank tracks in the Perfetto
  export, and prints the straggler root-cause table that
  ``membership.MembershipController.check_stragglers`` cites in its
  demote events.
* **statusz** — :class:`StatuszServer`, a tiny live ops endpoint every
  long-lived process (worker CLI, center server, elastic supervisor)
  serves, reusing the wire framing: health/uptime/current-span/last-N-
  events queries.  ``scripts/fleetz.py`` aggregates every process in a
  run dir into one table.

**Cost contract** (the §11 discipline): tracing is off unless the config
enables it (``tracing=true`` AND telemetry active).  Disabled,
:func:`active` returns the inert :data:`DISABLED` tracer whose
``enabled`` is ``False`` — every hot-path call site guards with that ONE
attribute check (machine-checked by tpulint's telemetry-hot-path pass,
which knows this module's span-emission API).

Module scope is stdlib + the telemetry shim — the tpulint schema-drift
checker loads this file jax-free to probe the span/statusz vocabulary
live.  The wire framing (statusz only) loads lazily by file path when
the package is absent, so no probe ever drags jax in.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

try:
    from . import telemetry
except ImportError:        # file-path load (jax-free lint probe): absolute
    from theanompi_tpu.utils import telemetry

#: The span event kind in the telemetry stream (consumed by
#: scripts/telemetry_report.py's trace assembly; schema-drift-pinned).
SPAN_EVENT = "span"

#: Emitted once when a statusz endpoint comes up (addr + role) — the
#: report renders it as an instant marker, fleetz uses the discovery
#: files (\ :func:`statusz_dir`) for the live sockets.
STATUSZ_EVENT = "statusz"

#: Fields every span event carries beyond the telemetry envelope
#: (ts/run/rank/ev).  ``side`` ∈ client/server; ``parent`` is None for a
#: root (round) span; ``t0``/``dt`` are start epoch-seconds and duration.
SPAN_FIELDS = ("name", "side", "trace", "span", "parent", "t0", "dt")

#: The critical-path component vocabulary (docs/design.md §17): every
#: second of a round is charged to exactly one of these.
COMPONENTS = ("compute", "stage", "wire", "queue", "apply")

#: Minimum field set a statusz ``health`` reply carries (probed live by
#: the schema-drift checker against a real socket round-trip).
STATUSZ_FIELDS = ("ok", "role", "id", "pid", "uptime_s", "run",
                  "spans", "current_span")

STATUSZ_OPS = ("health", "events", "flight")


def new_id(nbytes: int = 8) -> str:
    """A random hex id (16 hex chars by default) — unique across
    processes without coordination."""
    return os.urandom(int(nbytes)).hex()


def new_span_id() -> str:
    return new_id(8)


def new_trace_id() -> str:
    return new_id(8)


# -- spans --------------------------------------------------------------------

class Span:
    """One unit of traced work.  Created by :meth:`Tracer.begin` (root)
    or :meth:`child`; :meth:`end` emits the ``span`` event.  ``ctx()`` is
    the wire-header form of this span's context — a child created on the
    other side of the wire parents to THIS span."""

    __slots__ = ("_tracer", "trace", "span", "parent", "name", "t0",
                 "_fields")

    def __init__(self, tracer: "Tracer", name: str,
                 trace: Optional[str] = None, parent: Optional[str] = None,
                 **fields):
        self._tracer = tracer
        self.trace = trace or new_trace_id()
        self.span = new_span_id()
        self.parent = parent
        self.name = str(name)
        self.t0 = time.time()
        self._fields = dict(fields)

    def ctx(self) -> Dict[str, str]:
        """The wire-header trace context: ``{"t": trace_id, "s": span_id}``
        — what a request header carries so the server span can parent to
        this one."""
        return {"t": self.trace, "s": self.span}

    def child(self, name: str, **fields) -> "Span":
        return Span(self._tracer, name, trace=self.trace, parent=self.span,
                    **fields)

    def note(self, **fields) -> None:
        """Attach fields to be emitted with :meth:`end`."""
        self._fields.update(fields)

    def end(self, **fields) -> dict:
        """Finish the span: one ``span`` event into the stream."""
        self._fields.update(fields)
        return self._tracer._emit(self, time.time() - self.t0)


class Tracer:
    """Per-process span factory riding the telemetry stream.

    Thread-safe: islands (threads) share one tracer; ``current`` (the
    statusz current-span snapshot) is REPLACED atomically, never mutated
    in place, and the counters update under a lock."""

    enabled = True

    def __init__(self, telemetry_=None):
        self.telemetry = telemetry_
        self._lock = threading.Lock()
        self.spans = 0                 # spans emitted by this process
        self.current: Optional[dict] = None   # last begun, for statusz

    def _tm(self):
        return self.telemetry if self.telemetry is not None \
            else telemetry.active()

    def begin(self, name: str, trace: Optional[str] = None,
              parent: Optional[str] = None, **fields) -> Span:
        sp = Span(self, name, trace=trace, parent=parent, **fields)
        with self._lock:
            self.current = {"name": sp.name, "trace": sp.trace,
                            "span": sp.span, "t0": round(sp.t0, 3)}
        return sp

    def _emit(self, sp: Span, dt: float) -> dict:
        fields = {k: v for k, v in sp._fields.items() if v is not None}
        ev = dict(name=sp.name, side=fields.pop("side", "client"),
                  trace=sp.trace, span=sp.span, parent=sp.parent,
                  t0=round(sp.t0, 6), dt=round(dt, 6), **fields)
        tm = self._tm()
        if tm.enabled:
            tm.event(SPAN_EVENT, **ev)
        with self._lock:
            self.spans += 1
            cur = self.current
            if cur is not None and cur.get("span") == sp.span:
                self.current = None
        return ev


class _DisabledTracer:
    """The inert tracer: one attribute check is the whole hot-path cost."""

    enabled = False
    spans = 0
    current = None

    def begin(self, name, trace=None, parent=None, **fields):
        return None

    def _tm(self):
        return telemetry.DISABLED


DISABLED = _DisabledTracer()

_ACTIVE: Any = DISABLED


def active():
    """The process-wide tracer — :data:`DISABLED` until :func:`init`
    enables one.  Components (islands, exchanger) read it lazily."""
    return _ACTIVE


def init(config: Optional[dict] = None):
    """(Re)initialize process-wide tracing from a worker config.

    Enabled only when ``tracing=true`` (or a truthy string) AND the
    process telemetry is enabled — span events ride the telemetry
    stream, so a tracer without a registry would trace into the void."""
    global _ACTIVE
    config = config or {}
    t = config.get("tracing", False)
    if isinstance(t, str):
        t = t.lower() not in ("false", "0", "")
    if t and telemetry.active().enabled:
        _ACTIVE = Tracer()
    else:
        _ACTIVE = DISABLED
    return _ACTIVE


# -- one-shot emit helpers (the wire layer + center server call these) --------

def emit_wire_span(tm, trace: dict, op: str, span: Optional[str] = None,
                   t0: Optional[float] = None, dt: float = 0.0,
                   q: Optional[float] = None, a: Optional[float] = None,
                   dedup: bool = False, ok: bool = True,
                   err: Optional[str] = None, retries: int = 0) -> None:
    """One client-side ``wire.<op>`` span event — called by
    ``WireClient.request`` when the caller passed trace context.  The
    span id was minted BEFORE the request (it is the ``s`` the server's
    span parents to); all retries of the request share it, so 'retries
    share the trace id' holds by construction."""
    ev = {"name": f"wire.{op}", "side": "client",
          "trace": trace.get("t"), "span": span or new_span_id(),
          "parent": trace.get("s"),
          "t0": round(t0 if t0 is not None else time.time() - dt, 6),
          "dt": round(dt, 6), "ok": bool(ok)}
    if q is not None:
        ev["q"] = q
    if a is not None:
        ev["a"] = a
    if dedup:
        ev["dedup"] = True
    if retries:
        ev["retries"] = int(retries)
    if err:
        ev["err"] = str(err)[:160]
    tm.event(SPAN_EVENT, **ev)


def emit_server_span(tm, trace: dict, op: str, t0: float, dt: float,
                     q: Optional[float] = None, a: Optional[float] = None,
                     island=None, dedup: bool = False,
                     ok: bool = True) -> None:
    """One server-side ``center.<op>`` span event — called by the center
    handler for every request that carried trace context, parented to the
    client's ``wire.<op>`` span.  A deduplicated twin (retry or chaos
    duplicate of an op that already landed) is tagged ``dedup=True`` so
    the trace assembly joins the client span to the ONE applied span and
    never double-counts the twin on the critical path."""
    ev = {"name": f"center.{op}", "side": "server",
          "trace": trace.get("t"), "span": new_span_id(),
          "parent": trace.get("s"),
          "t0": round(t0, 6), "dt": round(dt, 6), "ok": bool(ok)}
    if q is not None:
        ev["q"] = q
    if a is not None:
        ev["a"] = a
    if island is not None:
        ev["island"] = island
    if dedup:
        ev["dedup"] = True
    tm.event(SPAN_EVENT, **ev)


# -- the wire framing, loaded without dragging a backend in -------------------

_WIRE: Any = None


def _wire():
    """``parallel/wire.py`` for the statusz framing.  The already-imported
    package module when the process has it (every runtime process does);
    a FILE-path load otherwise — importing ``theanompi_tpu.parallel``
    executes its ``__init__`` (jax), which the jax-free consumers (lint
    probes, ``scripts/fleetz.py``) must never pay."""
    global _WIRE
    if _WIRE is None:
        import sys
        mod = sys.modules.get("theanompi_tpu.parallel.wire")
        if mod is None:
            import importlib.util
            path = os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "parallel", "wire.py")
            spec = importlib.util.spec_from_file_location(
                "_tracing_wire", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        _WIRE = mod
    return _WIRE


# -- statusz: the live ops endpoint -------------------------------------------

def statusz_dir(run_dir: str) -> str:
    return os.path.join(run_dir, "statusz")


class StatuszServer:
    """A tiny live ops socket (wire framing, docs/design.md §17).

    Ops: ``health`` → the :data:`STATUSZ_FIELDS` snapshot (plus
    caller ``extra()`` fields and the iteration gauge when the process
    exports one); ``events`` → the last N telemetry flight-ring events.
    ``run_dir`` registers a discovery file under ``<run_dir>/statusz/``
    (atomic write) that ``scripts/fleetz.py`` aggregates; it is removed
    on a clean :meth:`stop` so only live-or-crashed processes remain
    listed (fleetz marks unreachable ones DOWN)."""

    def __init__(self, role: str, ident: Any = 0,
                 run_dir: Optional[str] = None, telemetry_=None,
                 tracer_=None, extra: Optional[Callable[[], dict]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 idle_timeout_s: float = 30.0):
        self.role = str(role)
        self.ident = ident
        self.run_dir = run_dir
        self.telemetry = telemetry_
        self.tracer = tracer_
        self.extra = extra
        self.host = host
        self.port = int(port)
        self.idle_timeout_s = float(idle_timeout_s)
        self.t0 = time.time()
        self._srv = None
        self._thread: Optional[threading.Thread] = None
        self._doc_path: Optional[str] = None

    def _tm(self):
        return self.telemetry if self.telemetry is not None \
            else telemetry.active()

    def _tr(self):
        return self.tracer if self.tracer is not None else active()

    def status(self) -> dict:
        tm = self._tm()
        tr = self._tr()
        out = {"ok": True, "role": self.role, "id": self.ident,
               "pid": os.getpid(),
               "uptime_s": round(time.time() - self.t0, 1),
               "run": getattr(tm, "run_id", None),
               "spans": getattr(tr, "spans", 0),
               "current_span": getattr(tr, "current", None)}
        it = tm.gauges.get("heartbeat.iter", tm.gauges.get("iter")) \
            if getattr(tm, "gauges", None) else None
        if it is not None:
            out["iter"] = it
        tail = tm.tail(1) if tm.enabled else []
        if tail:
            out["last_event"] = {"ev": tail[-1].get("ev"),
                                 "ts": tail[-1].get("ts")}
        if self.extra is not None:
            try:
                out.update(self.extra() or {})
            except Exception:
                pass               # a status probe must never crash serving
        return out

    def start(self) -> Tuple[str, int]:
        import socketserver
        w = _wire()
        outer = self
        idle = self.idle_timeout_s

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.request.settimeout(idle)
                try:
                    while True:
                        header, _ = w.recv_msg(self.request)
                        op = header.get("op")
                        if op == "health":
                            w.send_msg(self.request, outer.status())
                        elif op == "events":
                            n = int(header.get("n", 16))
                            w.send_msg(self.request,
                                       {"ok": True,
                                        "events": outer._tm().tail(n)})
                        elif op == "flight":
                            # fleet-wide flight dump (§20): a fleet-scoped
                            # alert asks every process for its ring — the
                            # what-was-everyone-doing trail, on demand
                            tm = outer._tm()
                            path = None
                            if tm.enabled:
                                path = tm.dump_flight(
                                    reason=str(header.get(
                                        "reason", "statusz flight op")))
                            w.send_msg(self.request,
                                       {"ok": True, "path": path})
                        else:
                            w.send_msg(self.request,
                                       {"ok": False,
                                        "error": f"unknown statusz op "
                                                 f"{op!r} (have "
                                                 f"{STATUSZ_OPS})"})
                except Exception:
                    return         # peer gone / idle / bad frame: drop it

        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._srv = socketserver.ThreadingTCPServer((self.host, self.port),
                                                    Handler)
        self._srv.daemon_threads = True
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name=f"statusz-{self.role}")
        self._thread.start()
        host, port = self._srv.server_address[:2]
        if self.run_dir:
            d = statusz_dir(self.run_dir)
            try:
                os.makedirs(d, exist_ok=True)
                self._doc_path = os.path.join(
                    d, f"{self.role}_{self.ident}.json")
                tmp = f"{self._doc_path}.tmp{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump({"role": self.role, "id": self.ident,
                               "pid": os.getpid(), "host": host,
                               "port": port, "ts": time.time()}, f)
                os.replace(tmp, self._doc_path)
            except OSError:
                self._doc_path = None   # discovery is best-effort
        tm = self._tm()
        if tm.enabled:
            tm.event(STATUSZ_EVENT, role=self.role, id=self.ident,
                     addr=f"{host}:{port}")
        return host, port

    def stop(self, deregister: bool = True) -> None:
        """Shut the socket down; ``deregister=False`` (a crashed/failing
        exit path) LEAVES the discovery doc behind so fleetz lists the
        process DOWN — only a clean exit removes its roster entry (a
        SIGKILLed process never runs stop at all, same verdict)."""
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
        if self._thread is not None:
            # bounded join (tpulint daemon-discipline): nothing of the
            # endpoint may outlive stop() into a same-port restart
            self._thread.join(timeout=5)
            self._thread = None
        if self._doc_path is not None:
            if deregister:
                try:
                    os.remove(self._doc_path)
                except OSError:
                    pass
            self._doc_path = None


def statusz_query(addr: str, op: str = "health", n: int = 16,
                  timeout_s: float = 2.0) -> dict:
    """One statusz round-trip (``host:port``) — raises on an unreachable
    endpoint (fleetz renders that as DOWN)."""
    import socket
    w = _wire()
    host, port = str(addr).rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=timeout_s)
    try:
        s.settimeout(timeout_s)
        w.send_msg(s, {"op": op, "n": int(n)})
        header, _ = w.recv_msg(s)
        return header
    finally:
        s.close()


def read_statusz_docs(run_dir: str) -> List[dict]:
    """All discovery docs under ``<run_dir>/statusz/`` (sorted by role
    then id) — the fleet roster fleetz dials."""
    d = statusz_dir(run_dir)
    docs: List[dict] = []
    if not os.path.isdir(d):
        return docs
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                doc = json.load(f)
            docs.append(doc)
        except (ValueError, OSError):
            continue
    docs.sort(key=lambda x: (str(x.get("role")), str(x.get("id"))))
    return docs
