"""Stall watchdog — failure detection for hung steps/collectives.

SURVEY.md §5 lists failure detection as an auxiliary subsystem the reference
lacked entirely (a wedged MPI collective hung the job silently until the
cluster scheduler killed it).  On TPU the same failure shape exists — a hung
host↔device transfer or a peer dropping out of a multi-host collective
blocks the main thread inside a jax call — so detection must run OFF the
main thread.

:class:`StallWatchdog` is a daemon thread fed by per-iteration heartbeats
from the worker loop (``stall_timeout`` config, 0 = off).  On a stall it
emits one diagnostic — elapsed time, the last heartbeat label, and a
traceback dump of every live thread (`faulthandler`) showing exactly where
the main thread is stuck — and invokes an optional callback (e.g. emergency
checkpoint, or ``os._exit`` for a supervisor-restart recovery story, which
pairs with the per-epoch ``ckpt_dir``/``resume`` flow).
"""

from __future__ import annotations

import faulthandler
import sys
import threading
import time
from typing import Callable, Optional

from . import telemetry


class StallWatchdog:
    """Daemon heartbeat monitor.

    ``on_stall(elapsed_s, last_label)`` fires once per stall episode (it
    re-arms when heartbeats resume).  The default handler prints the
    diagnostic and all-thread tracebacks to stderr.
    """

    def __init__(self, timeout_s: float,
                 on_stall: Optional[Callable[[float, str], None]] = None,
                 poll_s: Optional[float] = None,
                 first_timeout_s: Optional[float] = None):
        self.timeout_s = float(timeout_s)
        # before the FIRST beat the job is usually compiling (minutes for a
        # big model) — use a much larger threshold so startup isn't a
        # spurious "stall"
        self.first_timeout_s = float(first_timeout_s) \
            if first_timeout_s is not None else 10.0 * self.timeout_s
        self.on_stall = on_stall or self._default_handler
        self.poll_s = poll_s if poll_s is not None else \
            max(0.05, self.timeout_s / 4)
        self._last_beat = time.monotonic()
        self._last_label = "(no heartbeat yet)"
        self._beaten = False
        # single-writer re-arm protocol (tpulint shared-state-race): the
        # hot loop bumps `_beat_seq` (ONLY beat writes it), the monitor
        # remembers which beat it fired for in ITS local state — no
        # attribute is written from two threads, so there is no window
        # where a beat landing between the monitor's check and set could
        # be lost or double-fire a stall
        self._beat_seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stall_count = 0

    # -- heartbeat (called from the worker hot loop) ------------------------

    def beat(self, label: str = "") -> None:
        self._last_beat = time.monotonic()
        if label:
            self._last_label = label
        self._beaten = True
        self._beat_seq += 1          # re-arms the monitor (sole writer)
        # heartbeats feed the telemetry flight ring (ring-only: the stream
        # would drown in them) — the dump then shows exactly what the rank
        # was doing in the window before a stall/crash
        tm = telemetry.active()
        if tm.enabled:
            tm.event("beat", ring_only=True, label=label)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "StallWatchdog":
        if self.timeout_s <= 0:
            return self
        self._thread = threading.Thread(target=self._monitor, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll_s + 1)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- monitor ------------------------------------------------------------

    def _monitor(self) -> None:
        fired_for = -1               # monitor-local: last beat seq fired on
        while not self._stop.wait(self.poll_s):
            seq = self._beat_seq
            elapsed = time.monotonic() - self._last_beat
            threshold = self.timeout_s if self._beaten else self.first_timeout_s
            if elapsed > threshold and seq != fired_for:
                fired_for = seq      # one fire per stall episode; a new
                self.stall_count += 1     # beat advances seq and re-arms
                try:
                    self.on_stall(elapsed, self._last_label)
                except Exception as e:     # a broken handler must not kill
                    print(f"watchdog handler failed: {e!r}", file=sys.stderr)

    def _default_handler(self, elapsed: float, label: str) -> None:
        print(f"WATCHDOG: no progress for {elapsed:.1f}s "
              f"(timeout {self.timeout_s:.1f}s); last heartbeat: {label}. "
              f"Dumping all thread stacks:", file=sys.stderr, flush=True)
        faulthandler.dump_traceback(file=sys.stderr)
        # the last few flight-recorder events inline: what the rank was
        # doing when it hung — a tunnel-window stall is then diagnosable
        # from the console log alone, no record_dir needed
        tail = telemetry.active().tail(8)
        if tail:
            print("WATCHDOG: last telemetry events before the stall:",
                  file=sys.stderr)
            for ev in tail:
                bits = " ".join(f"{k}={v}" for k, v in ev.items()
                                if k not in ("run", "rank"))
                print(f"  {bits}", file=sys.stderr)
            sys.stderr.flush()
