"""Wall-clock section timing + metric accumulation.

TPU-native rebuild of Theano-MPI's ``theanompi/lib/recorder.py``
(SURVEY.md §2.10): per-iteration section timers (``t_train`` / ``t_comm`` /
``t_wait`` / ``t_load``), images/sec derivation, train cost/error and val
top-1/top-5 accumulation, periodic console printing, and per-epoch dumps for
offline plotting.  The paper's "time per 5120 images" tables come from this
component, so the bucket names and the 5120-image accounting are preserved.

Additions over the reference: JSONL record emission (alongside the ``.npy``
dumps) and an images/sec/chip derivation — the north-star metric in
``BASELINE.json``.
"""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from . import telemetry

# The reference reports "time per 5120 images" (40 batches of 128).
IMAGES_PER_REPORT = 5120

# `load` = waiting on the data source (pure dequeue wait under para_load);
# `stage` = consumer-thread host stack + device_put (≈0 when the parallel
# loader's window producer stages dispatch inputs off the hot path) — the
# split makes the producer/consumer overlap win visible in records.
# `compile` = building the iteration functions (worker.py brackets
# compile_iter_fns): the XLA compile on a cold start, the executable-cache
# deserialize (~seconds) on a warm one — the bucket makes the AOT cache's
# win (and a resume recompiling from scratch) visible per run.
# The list itself lives in telemetry.PHASES — ONE source of truth for the
# recorder buckets, the t_<section> record keys below, and the telemetry
# phase-event names (the tpulint schema-drift checker guards the sync).
SECTIONS = telemetry.PHASES

# the per-print record carries every section except `val` (val time is
# reported cumulatively by print_val_info) — derived, so it cannot drift
RECORD_KEYS = tuple("t_" + s for s in SECTIONS if s != "val")


class Recorder:
    """Three-bucket (plus load/val) wall-clock recorder.

    Usage mirrors the reference: the worker hot loop brackets each phase with
    ``recorder.start()`` / ``recorder.end('train')``, accumulates metrics with
    ``train_error`` / ``val_error``, and prints every ``printFreq`` iterations
    with ``print_train_info(count)``.
    """

    # the process-wide telemetry registry (worker.py re-points this at the
    # live instance); the class default is the inert no-op, so recorders
    # built outside a Worker cost one attribute check per bracket
    telemetry = telemetry.DISABLED

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.verbose: bool = config.get("verbose", True)
        self.rank: int = config.get("rank", 0)
        self.size: int = config.get("size", 1)
        self.printFreq: int = config.get("printFreq", 40)
        self.record_dir: str = config.get("record_dir", "./inc")

        self._t0: Optional[float] = None
        self.t_sec: Dict[str, float] = defaultdict(float)  # running, since last print
        self.t_sec_total: Dict[str, float] = defaultdict(float)

        self._train_cost: List[float] = []
        self._train_error: List[float] = []
        self._val_cost: List[float] = []
        self._val_error: List[float] = []
        self._val_error_top5: List[float] = []

        self.n_images: int = 0  # images since last print
        self.n_images_total: int = 0
        self.epoch_records: List[dict] = []
        self._all_records: List[dict] = []
        self._wall_start = time.time()
        self._last_print_wall = self._wall_start

    # -- timing ------------------------------------------------------------

    def start(self) -> None:
        self._t0 = time.time()

    def end(self, section: str) -> float:
        assert self._t0 is not None, "Recorder.end() without start()"
        dt = time.time() - self._t0
        self.t_sec[section] += dt
        self.t_sec_total[section] += dt
        self._t0 = None
        # per-dispatch phase events: one histogram sample + one stream
        # event per bracket — the raw material for telemetry_report's
        # tail percentiles and straggler ranking.  Disabled ≡ one
        # attribute check.
        if self.telemetry.enabled:
            self.telemetry.phase(section, dt)
        return dt

    # -- metric accumulation ----------------------------------------------

    def train_error(self, count: int, cost, error, n_images: int = 0) -> None:
        """``cost``/``error`` may be host floats OR device scalars — they are
        only materialized at print cadence, so async dispatch stays async."""
        self._train_cost.append(cost)
        self._train_error.append(error)
        self.n_images += n_images
        self.n_images_total += n_images

    def val_error(self, count: int, cost: float, error: float, error_top5: float = 0.0) -> None:
        self._val_cost.append(float(cost))
        self._val_error.append(float(error))
        self._val_error_top5.append(float(error_top5))

    # -- reporting ---------------------------------------------------------

    def images_per_sec(self) -> float:
        """Throughput since the last print, from WALL time — honest whether
        the hot loop dispatches asynchronously or blocks per iteration (the
        section buckets only sum to wall time in blocking mode)."""
        t = time.time() - self._last_print_wall
        return self.n_images / t if t > 0 else 0.0

    def time_per_5120(self) -> float:
        """The reference's headline unit: seconds per 5120 images processed."""
        ips = self.images_per_sec()
        return IMAGES_PER_REPORT / ips if ips > 0 else float("inf")

    def print_train_info(self, count: int, stride: int = 1) -> Optional[dict]:
        """``stride`` = steps per train_iter dispatch (``steps_per_call``):
        count then only visits multiples of it.  The gate fires once every
        ``ceil(printFreq / stride)`` dispatches — at least printFreq steps
        apart even when stride does not divide printFreq (the old
        ``count % printFreq < stride`` residue test double-fired inside one
        window in that case) — and the averaging slice counts DISPATCH
        entries, not steps.  Returns the emitted record (the worker keys
        its periodic gauge snapshots off it), or None when gated."""
        k = max(1, -(-self.printFreq // stride))      # ceil division
        if (count // stride) % k != 0:
            return None
        # materializing device scalars happens HERE, once per printFreq iters
        cost = float(np.mean([np.asarray(c) for c in self._train_cost[-k:]])) \
            if self._train_cost else float("nan")
        err = float(np.mean([np.asarray(e) for e in self._train_error[-k:]])) \
            if self._train_error else float("nan")
        rec = {"iter": count, "cost": cost, "error": err}
        for key, s in zip(RECORD_KEYS, (s for s in SECTIONS if s != "val")):
            rec[key] = self.t_sec[s]
        rec.update(
            images_per_sec=self.images_per_sec(),
            images_per_sec_per_chip=self.images_per_sec() / max(self.size, 1),
            time_per_5120=self.time_per_5120(),
            wall=time.time() - self._wall_start,
        )
        self._all_records.append(rec)
        if self.telemetry.enabled:
            # the per-rank throughput timeline telemetry_report draws
            self.telemetry.event("train_record", **rec)
        if self.verbose and self.rank == 0:
            print(
                f"iter {count}: cost {cost:.4f} err {err:.4f} | "
                f"train {rec['t_train']:.3f}s comm {rec['t_comm']:.3f}s "
                f"wait {rec['t_wait']:.3f}s load {rec['t_load']:.3f}s "
                f"stage {rec['t_stage']:.3f}s"
                + (f" compile {rec['t_compile']:.3f}s"
                   if rec['t_compile'] > 0 else "") + " | "
                f"{rec['images_per_sec']:.1f} img/s "
                f"({rec['images_per_sec_per_chip']:.1f}/chip, "
                f"{rec['time_per_5120']:.2f}s per 5120)",
                flush=True,
            )
        for s in SECTIONS:
            self.t_sec[s] = 0.0
        self.n_images = 0
        self._last_print_wall = time.time()
        return rec

    def print_val_info(self, count: int) -> dict:
        rec = {
            "iter": count,
            "val_cost": float(np.mean(self._val_cost)) if self._val_cost else float("nan"),
            "val_error": float(np.mean(self._val_error)) if self._val_error else float("nan"),
            "val_error_top5": (
                float(np.mean(self._val_error_top5)) if self._val_error_top5 else float("nan")
            ),
            "t_val": self.t_sec_total["val"],
            # cumulative: shows compile going to ~0 on a cache-hit resume
            "t_compile": self.t_sec_total["compile"],
        }
        self.epoch_records.append(rec)
        if self.telemetry.enabled:
            self.telemetry.event("val_record", **rec)
        if self.verbose and self.rank == 0:
            print(
                f"validation @ iter {count}: cost {rec['val_cost']:.4f} "
                f"top-1 err {rec['val_error']:.4f} top-5 err {rec['val_error_top5']:.4f}",
                flush=True,
            )
        self._val_cost, self._val_error, self._val_error_top5 = [], [], []
        return rec

    def clear_train_info(self) -> None:
        self._train_cost, self._train_error = [], []

    # -- persistence (reference dumps .npy records; we add JSONL) ----------

    def save(self, record_dir: Optional[str] = None) -> None:
        d = record_dir or self.record_dir
        os.makedirs(d, exist_ok=True)
        np.save(os.path.join(d, f"inforec_rank{self.rank}.npy"),
                np.array(self._all_records, dtype=object))
        with open(os.path.join(d, f"inforec_rank{self.rank}.jsonl"), "w") as f:
            for rec in self._all_records:
                f.write(json.dumps(rec) + "\n")
            for rec in self.epoch_records:
                f.write(json.dumps(rec) + "\n")

    def load(self, record_dir: Optional[str] = None) -> None:
        """Restore BOTH record lists, preferring the JSONL (the only dump
        that holds the epoch/validation records — the ``.npy`` carries the
        train records alone).  A resumed run's next ``save()`` then
        rewrites the JSONL with the pre-resume epoch lines intact:
        save → load → save is lossless (json float round-trips are exact).

        Epoch records are recognized by their ``val_cost`` key — the field
        ``print_val_info`` always writes and ``print_train_info`` never
        does."""
        d = record_dir or self.record_dir
        jl = os.path.join(d, f"inforec_rank{self.rank}.jsonl")
        if os.path.exists(jl):
            train: List[dict] = []
            epoch: List[dict] = []
            with open(jl) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        # a worker killed mid-save leaves a truncated last
                        # line; a resume must shrug it off, not crash-loop
                        # the supervisor on every retry
                        continue
                    (epoch if "val_cost" in rec else train).append(rec)
            self._all_records, self.epoch_records = train, epoch
            return
        path = os.path.join(d, f"inforec_rank{self.rank}.npy")
        if os.path.exists(path):
            self._all_records = list(np.load(path, allow_pickle=True))
