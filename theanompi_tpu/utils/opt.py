"""Optimizer update builders.

TPU-native rebuild of Theano-MPI's ``theanompi/lib/opt.py`` (SURVEY.md §2.7):
builders that in the reference returned Theano update lists — vanilla SGD,
momentum SGD, Nesterov momentum — plus weight decay and the ``n_subb``
sub-batch gradient-accumulation machinery (the ``pre_model_iter_fn`` pattern).

Here each builder returns an ``(init_fn, update_fn)`` pair over pytrees, pure
and jittable; gradient accumulation is expressed as a ``lax.scan`` over
microbatches in the trainer's compiled step rather than as pre-compiled
sub-batch functions.  The math matches the reference's conventions:

  momentum:  v' = mu*v - lr*(g + wd*p);  p' = p + v'
  nesterov:  v' = mu*v - lr*(g + wd*p);  p' = p + mu*v' - lr*(g + wd*p)

Learning rate is carried in a mutable hyperparameter dict so the model's
``adjust_hyperp(epoch)`` / ``scale_lr(size)`` contract (SURVEY.md §2.5) works
without recompilation — the lr enters the jitted step as a traced scalar.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class OptPair(NamedTuple):
    init: Callable
    update: Callable  # (grads, opt_state, params, lr) -> (new_params, new_opt_state)


def _zeros_like_tree(params):
    return jax.tree.map(jnp.zeros_like, params)


def sgd(weight_decay: float = 0.0) -> OptPair:
    """Vanilla SGD: p' = p - lr*(g + wd*p)."""

    def init(params):
        return ()

    def update(grads, opt_state, params, lr):
        new_params = jax.tree.map(
            lambda p, g: p - lr * (g + weight_decay * p), params, grads
        )
        return new_params, opt_state

    return OptPair(init, update)


def momentum(mu: float = 0.9, weight_decay: float = 0.0001) -> OptPair:
    """Classical momentum SGD — the reference model zoo's default
    (AlexNet/VGG/GoogLeNet all train with momentum 0.9, wd 5e-4/1e-4)."""

    def init(params):
        return _zeros_like_tree(params)

    def update(grads, vel, params, lr):
        new_vel = jax.tree.map(
            lambda v, g, p: mu * v - lr * (g + weight_decay * p), vel, grads, params
        )
        new_params = jax.tree.map(lambda p, v: p + v, params, new_vel)
        return new_params, new_vel

    return OptPair(init, update)


def nesterov(mu: float = 0.9, weight_decay: float = 0.0001) -> OptPair:
    """Nesterov accelerated gradient, in the same form Theano/Lasagne used."""

    def init(params):
        return _zeros_like_tree(params)

    def update(grads, vel, params, lr):
        step = jax.tree.map(lambda g, p: lr * (g + weight_decay * p), grads, params)
        new_vel = jax.tree.map(lambda v, s: mu * v - s, vel, step)
        new_params = jax.tree.map(
            lambda p, v, s: p + mu * v - s, params, new_vel, step
        )
        return new_params, new_vel

    return OptPair(init, update)


OPTIMIZERS = {
    "sgd": sgd,
    "momentum": momentum,
    "nesterov": nesterov,
}


def get_optimizer(name: str, **kwargs) -> OptPair:
    try:
        return OPTIMIZERS[name](**kwargs)
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; have {sorted(OPTIMIZERS)}")
