"""Optimizer update builders.

TPU-native rebuild of Theano-MPI's ``theanompi/lib/opt.py`` (SURVEY.md §2.7):
builders that in the reference returned Theano update lists — vanilla SGD,
momentum SGD, Nesterov momentum — plus weight decay and the ``n_subb``
sub-batch gradient-accumulation machinery (the ``pre_model_iter_fn`` pattern).

Here each builder returns an ``(init_fn, update_fn)`` pair over pytrees, pure
and jittable; gradient accumulation is expressed as a ``lax.scan`` over
microbatches in the trainer's compiled step rather than as pre-compiled
sub-batch functions.  The math matches the reference's conventions:

  momentum:  v' = mu*v - lr*(g + wd*p);  p' = p + v'
  nesterov:  v' = mu*v - lr*(g + wd*p);  p' = p + mu*v' - lr*(g + wd*p)

Learning rate is carried in a mutable hyperparameter dict so the model's
``adjust_hyperp(epoch)`` / ``scale_lr(size)`` contract (SURVEY.md §2.5) works
without recompilation — the lr enters the jitted step as a traced scalar.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class OptPair(NamedTuple):
    init: Callable
    update: Callable  # (grads, opt_state, params, lr) -> (new_params, new_opt_state)


def _zeros_like_tree(params):
    return jax.tree.map(jnp.zeros_like, params)


def sgd(weight_decay: float = 0.0) -> OptPair:
    """Vanilla SGD: p' = p - lr*(g + wd*p)."""

    def init(params):
        return ()

    def update(grads, opt_state, params, lr):
        new_params = jax.tree.map(
            lambda p, g: p - lr * (g + weight_decay * p), params, grads
        )
        return new_params, opt_state

    return OptPair(init, update)


def momentum(mu: float = 0.9, weight_decay: float = 0.0001) -> OptPair:
    """Classical momentum SGD — the reference model zoo's default
    (AlexNet/VGG/GoogLeNet all train with momentum 0.9, wd 5e-4/1e-4)."""

    def init(params):
        return _zeros_like_tree(params)

    def update(grads, vel, params, lr):
        new_vel = jax.tree.map(
            lambda v, g, p: mu * v - lr * (g + weight_decay * p), vel, grads, params
        )
        new_params = jax.tree.map(lambda p, v: p + v, params, new_vel)
        return new_params, new_vel

    return OptPair(init, update)


def nesterov(mu: float = 0.9, weight_decay: float = 0.0001) -> OptPair:
    """Nesterov accelerated gradient, in the same form Theano/Lasagne used."""

    def init(params):
        return _zeros_like_tree(params)

    def update(grads, vel, params, lr):
        step = jax.tree.map(lambda g, p: lr * (g + weight_decay * p), grads, params)
        new_vel = jax.tree.map(lambda v, s: mu * v - s, vel, step)
        new_params = jax.tree.map(
            lambda p, v, s: p + mu * v - s, params, new_vel, step
        )
        return new_params, new_vel

    return OptPair(init, update)


def rmsprop(decay: float = 0.9, eps: float = 1e-8,
            weight_decay: float = 0.0) -> OptPair:
    """RMSprop — the WGAN paper's optimizer of choice (the reference's GAN
    models trained G/D with RMSprop, per-parameter adaptive scaling)."""

    def init(params):
        return _zeros_like_tree(params)

    def update(grads, sq_avg, params, lr):
        new_sq = jax.tree.map(
            lambda s, g: decay * s + (1 - decay) * g * g, sq_avg, grads)
        # weight decay is decoupled (outside the adaptive division), matching
        # adam below — so the config key means the same thing across
        # optimizers and doesn't vanish where gradient history is large.
        new_params = jax.tree.map(
            lambda p, g, s: p - lr * (g / (jnp.sqrt(s) + eps)
                                      + weight_decay * p),
            params, grads, new_sq)
        return new_params, new_sq

    return OptPair(init, update)


def adam(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
         weight_decay: float = 0.0) -> OptPair:
    """Adam with bias correction (LSGAN-style training)."""

    def init(params):
        # t mirrors the param tree (one counter per leaf) rather than being a
        # single root scalar: consumers that gate optimizer-state subtrees by
        # parameter path (the GAN n_critic cadence) must be able to freeze a
        # sub-network's bias-correction clock along with its m/v.
        return {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params),
                "t": jax.tree.map(lambda p: jnp.zeros((), jnp.int32), params)}

    def update(grads, st, params, lr):
        t = jax.tree.map(lambda t_: t_ + 1, st["t"])
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, st["m"], grads)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, st["v"], grads)

        def step(p, m_, v_, t_):
            tf = t_.astype(jnp.float32)
            bc1 = 1 - b1 ** tf
            bc2 = 1 - b2 ** tf
            return p - lr * ((m_ / bc1) / (jnp.sqrt(v_ / bc2) + eps)
                             + weight_decay * p)

        new_params = jax.tree.map(step, params, m, v, t)
        return new_params, {"m": m, "v": v, "t": t}

    return OptPair(init, update)


OPTIMIZERS = {
    "sgd": sgd,
    "momentum": momentum,
    "nesterov": nesterov,
    "rmsprop": rmsprop,
    "adam": adam,
}


def ema_wrap(opt: OptPair, decay: float) -> OptPair:
    """Polyak/EMA parameter averaging as an optimizer wrapper (config
    ``ema_decay``): a shadow copy tracks ``decay·ema + (1−decay)·params``
    after every update; validation and inference read the shadow (smoother
    late-training weights — the modern eval default the reference
    predates).  The shadow initializes AT the params, so no zero-init bias
    correction is needed."""
    decay = float(decay)
    assert 0.0 < decay < 1.0, f"ema_decay must be in (0, 1); got {decay}"

    def init(params):
        # Seed the shadow from whatever init receives: the REAL params in
        # the plain case (so even a consumer that reverts optimizer-state
        # subtrees to their init — the GANs' n_critic gate — reverts G's
        # shadow to G's params, not to zeros), or zero_opt's shape template
        # (each worker's chunk differs and the boxed replicate broadcasts
        # one template) — there the t==0 branch in update() re-seeds from
        # the live pre-update params; both mechanisms agree in the plain
        # case.
        return {"inner": opt.init(params),
                "ema": jax.tree.map(jnp.asarray, params),
                "t": jnp.zeros((), jnp.int32)}

    def update(grads, st, params, lr):
        new_params, inner = opt.update(grads, st["inner"], params, lr)
        prev = jax.tree.map(
            lambda e, p: jnp.where(st["t"] == 0, p, e), st["ema"], params)
        ema = jax.tree.map(lambda e, p: decay * e + (1.0 - decay) * p,
                           prev, new_params)
        return new_params, {"inner": inner, "ema": ema, "t": st["t"] + 1}

    return OptPair(init, update)


def opt_state_specs(name: str, param_specs):
    """PartitionSpecs for an optimizer's state given the params' per-leaf
    specs (tensor-parallel models, ``parallel/tp.py``): every momentum/second
    -moment buffer is laid out exactly like the parameter it belongs to;
    adam's per-leaf step counters are scalars (replicated)."""
    from jax.sharding import PartitionSpec as P
    from ..parallel.steps import _is_spec

    if name == "sgd":
        return ()
    if name in ("momentum", "nesterov", "rmsprop"):
        return param_specs
    if name == "adam":
        scalars = jax.tree.map(lambda s: P(), param_specs, is_leaf=_is_spec)
        return {"m": param_specs, "v": param_specs, "t": scalars}
    raise ValueError(f"no state-spec rule for optimizer {name!r}")


def get_optimizer(name: str, **kwargs) -> OptPair:
    try:
        return OPTIMIZERS[name](**kwargs)
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; have {sorted(OPTIMIZERS)}")
