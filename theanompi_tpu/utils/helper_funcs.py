"""Buffer/serialization helpers.

TPU-native rebuild of Theano-MPI's ``theanompi/lib/helper_funcs.py``
(SURVEY.md §2.10): where the reference exposed raw ``bufint`` GPUArray views
and a numpy↔MPI dtype map so mpi4py could address device memory, the
TPU-native equivalents are pytree↔flat-vector packing (the ring/compressed
exchanger strategies operate on one contiguous fp32 vector, like the
reference's concatenated parameter buffer) and per-layer ``.npy``
save/load (``save_model`` / ``load_model`` via ``Weight.save``).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# pytree <-> flat vector  (reference: the contiguous GPUArray param buffer the
# exchanger strategies walked with bufint views)
# ---------------------------------------------------------------------------

def tree_size(tree) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


def flatten_tree(tree, pad_to_multiple_of: int = 1) -> jnp.ndarray:
    """Concatenate all leaves into one fp32 vector (optionally zero-padded).

    Padding to a multiple of the worker count lets the ring strategies
    reduce-scatter equal chunks — the same trick the reference's ``asa``
    alltoall-sum-allgather strategy used on its concatenated buffer.
    """
    leaves = jax.tree.leaves(tree)
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])
    if pad_to_multiple_of > 1:
        pad = (-flat.shape[0]) % pad_to_multiple_of
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat


def unflatten_like(tree, flat: jnp.ndarray):
    """Inverse of :func:`flatten_tree` (ignores any zero padding)."""
    leaves, treedef = jax.tree.flatten(tree)
    out, ofs = [], 0
    for l in leaves:
        n = int(np.prod(l.shape))
        out.append(flat[ofs:ofs + n].reshape(l.shape).astype(l.dtype))
        ofs += n
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# save/load  (reference: save_model/load_model — per-layer .npy snapshots)
# ---------------------------------------------------------------------------

def _leaf_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        name = "_".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        yield name, leaf


def save_params(params, snapshot_dir: str) -> None:
    """Save a parameter pytree as one ``.npy`` per leaf (reference format:
    per-layer ``Weight.save`` into a snapshot dir)."""
    os.makedirs(snapshot_dir, exist_ok=True)
    for name, leaf in _leaf_paths(params):
        np.save(os.path.join(snapshot_dir, f"{name}.npy"), np.asarray(leaf))


def load_params(params_template, snapshot_dir: str):
    """Load a pytree saved by :func:`save_params`, shaped like the template."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params_template)
    leaves = []
    for path, leaf in flat:
        name = "_".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        arr = np.load(os.path.join(snapshot_dir, f"{name}.npy"))
        if arr.shape != leaf.shape:
            raise ValueError(f"checkpoint leaf {name}: shape {arr.shape} != "
                             f"expected {leaf.shape}")
        leaves.append(jnp.asarray(arr, dtype=leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def tree_allclose(a, b, rtol=1e-5, atol=1e-6) -> bool:
    oks = jax.tree.map(
        lambda x, y: bool(np.allclose(np.asarray(x), np.asarray(y), rtol, atol)), a, b
    )
    return all(jax.tree.leaves(oks))
