"""Device-time attribution from ``jax.profiler`` trace captures.

PR 4's ``phase.comm`` histograms time the HOST-side dispatch bracket: the
worker blocks until the exchange collective's result is ready and charges
the wall time to ``comm``.  That accounting goes blind the moment
collectives become async start/done pairs overlapped with backprop
(ROADMAP item 1): the host bracket then measures a queue push, and the
question that actually governs scaling — how much collective time is
*exposed* (serialized against compute) versus *hidden* (overlapped under
it) — is only answerable from the device timeline the XLA profiler
records.  The CUDA-aware-MPI characterization (PAPERS.md, 1810.11112)
makes the same point for GPU clusters: overlap of reduction with
backprop, not raw bandwidth, is the scaling variable.

This module is the ONE trace-proto reader in the repo (the glob/gzip/json
walk ``scripts/profile_model.py`` used to do inline, promoted and
tested).  ``jax.profiler.stop_trace`` writes
``<dir>/plugins/profile/<session>/<host>.trace.json.gz`` — gzipped
Chrome trace-event JSON where every executed HLO op is a complete
(``"ph": "X"``) event carrying ``args.hlo_op`` / ``args.hlo_module``.
That marker is the discriminator: host-side Python/runtime spans have no
``hlo_op``, so the parse needs no tensorboard plugin and stays stdlib.

**Attribution model.**  Op events are grouped into *lanes* (one
``(pid, tid)`` pair — a device plane's op line on TPU, one per-device
executor thread on the CPU sim).  Per lane the comm-op intervals and
compute-op intervals are union-merged, and

* ``comm_secs``      = Σ lanes measure(comm ∪)
* ``compute_secs``   = Σ lanes measure(compute ∪)
* ``exposed_comm_secs`` = Σ lanes [measure(comm ∪) −
  measure(comm ∪ ∩ compute ∪)] — collective time with NO compute running
  on the same lane, i.e. the serialized tail the step actually pays
* ``overlap_ratio``  = 1 − exposed_comm / comm  (None when no comm)

Comm ops are matched by HLO opcode prefix (``all-reduce``,
``all-gather``, ``reduce-scatter``, ``all-to-all``,
``collective-permute``, ... including their async ``-start``/``-done``
forms).  Each async pair is merged into ONE comm interval spanning
start-begin → done-end — the whole in-flight window — matched in
timestamp order per op class, same-lane first, then across lanes with
the merged interval landing on the START's lane (a runtime that parks
the done on a dedicated async-collective stream must not read as a
second, fully-exposed collective while the issuing lane's compute hides
the real one).

Host dispatch anchors: the worker loop and the standalone exchange tag
their dispatches with ``jax.profiler.TraceAnnotation`` spans named
:data:`TRAIN_DISPATCH_SPAN` / :data:`EXCHANGE_SPAN`; the parser counts
them so per-dispatch means don't depend on guessing the iteration count
from op repetitions.

Consumers: the worker's ``trace_dir`` capture feeds the result into the
PR 4 telemetry registry as ``device.*`` gauges (:func:`feed_telemetry` —
names pinned by the tpulint schema-drift checker), ``bench.py``'s
``BENCH_TRACE=1`` folds :data:`TRACE_ROW_COLUMNS` into the row JSON, and
``scripts/profile_model.py`` prints the same breakdown interactively.

No jax at module scope (the lint CLI and stdlib scripts import this for
the schema constants); :func:`capture` imports it lazily.
"""

from __future__ import annotations

import glob
import gzip
import json
import math
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

# Dispatch-anchor span names (host-side jax.profiler.TraceAnnotation):
# worker.py wraps each train_iter dispatch, exchanger.exchange wraps the
# standalone collective dispatch.  Constant strings — the parser matches
# them exactly.
TRAIN_DISPATCH_SPAN = "theanompi.train_dispatch"
EXCHANGE_SPAN = "theanompi.exchange"

# The device.* gauge vocabulary feed_telemetry emits — ONE list, guarded
# by the tpulint schema-drift checker so emitters and report consumers
# cannot desync (docs/design.md §13).
DEVICE_GAUGES = (
    "device.compute_secs",
    "device.comm_secs",
    "device.exposed_comm_secs",
    "device.overlap_ratio",
    "device.lanes",
)
PROFILE_EVENT = "device_profile"

# The bench-row columns BENCH_TRACE=1 adds (profile_row_fields emits
# exactly these keys; scripts/merge_matrix.py treats them — like any
# column — as unknown when absent, never as a regression).
TRACE_ROW_COLUMNS = (
    "overlap_ratio",
    "exposed_comm_secs",
    "device_compute_secs",
    "device_comm_secs",
    "device_mfu",
    # per-lane idle share between compute intervals inside the dispatch
    # window (ROADMAP item 2's pipeline-schedule acceptance metric):
    # span-weighted over compute lanes, 1 − busy/span per lane.  Exposed
    # same-lane collectives count as bubble deliberately — from the
    # compute pipeline's perspective a stall is a stall.
    "bubble_fraction",
)

# The bench-row columns BENCH_BUCKET_BYTES adds (the bucketed-wire rows,
# parallel/buckets.py): the configured bucket size and the collectives
# -per-exchange count the planner produced.  Declared HERE — the one
# jax-free schema home for bench-row vocabularies — so the tpulint
# schema-drift checker can pin bench's emission against it and guarantee
# it stays disjoint from TRACE_ROW_COLUMNS (a name collision would
# silently overwrite a trace column in the row JSON).
BUCKET_ROW_COLUMNS = (
    "bucket_bytes",
    "n_buckets",
)

# The bench-row columns pipelined rows (pp > 1 in BENCH_CFG) add — the
# :func:`pipeline_schedule_report` measurement: the tick-count bubble read
# off the hop events (exact when the capture verifies), the wall-time
# weighted bubble, and the verification bit itself.  Same jax-free schema
# -home discipline as BUCKET_ROW_COLUMNS; disjointness from the other two
# vocabularies is pinned in tests/test_pipeline_schedule.py.
PIPELINE_ROW_COLUMNS = (
    "pipeline_bubble_ticks",
    "pipeline_bubble_time",
    "pipeline_schedule_verified",
)

# The bench-row columns update-plane-sharding rows add (BENCH_USHARD=1 /
# BENCH_USHARD_REPORT=1; parallel/update_sharding.py, docs/design.md §23)
# — the :func:`update_state_report` measurement: per-chip update-plane
# bytes (optimizer state + exchanger extra, actual live-array bytes over
# worker count), the replicated-equivalent bytes the same session would
# hold without sharding, and their ratio (the ~N× headline).  Same
# jax-free schema-home discipline as the vocabularies above; disjointness
# is pinned in tests/test_update_sharding.py.
USHARD_ROW_COLUMNS = (
    "update_state_bytes_per_chip",
    "update_state_bytes_replicated",
    "update_state_shrink",
)

# The bench-row columns compression rows add (onebit/topk/powersgd
# strategies; ops/compress.py, ops/factor_pack.py, docs/design.md §24) —
# the :func:`compress_traffic_report` estimate: local HBM bytes one
# exchange moves through the compression pipeline, modeled at XLA-op
# granularity WITHOUT fusion credit (each jnp-level op reads its operands
# and writes its result — an upper bound for the unfused graph, exact for
# the single-pass Pallas kernels), before (legacy unfused ops) and after
# (fused kernel pipeline), plus the decode-stage ratio on its own (the
# scatter replacement is topk's headline).  Same jax-free schema-home
# discipline as the vocabularies above; disjointness is pinned in
# tests/test_compress_fusion.py.
COMPRESS_ROW_COLUMNS = (
    "compress_hbm_bytes_legacy",
    "compress_hbm_bytes_fused",
    "compress_hbm_shrink",
    "compress_decode_shrink",
)

# HLO opcodes whose device time is collective/communication time.  Async
# pairs (`<op>-start` / `<op>-done`) share the prefix and match too.
COMM_OP_PREFIXES = (
    "all-reduce",
    "all-gather",
    "all-to-all",
    "reduce-scatter",
    "collective-permute",
    "collective-broadcast",
    "ragged-all-to-all",
    "send",
    "recv",
)

_SUFFIX_RE = re.compile(r"\.\d+$")


def op_class(name: str) -> str:
    """HLO instruction name → op class: strip the unique ``.N`` suffix
    (``all-reduce.1`` → ``all-reduce``), keep fusion/async qualifiers —
    they distinguish genuinely different kinds of device time."""
    return _SUFFIX_RE.sub("", str(name))


def is_comm_op(name: str) -> bool:
    """Whether one HLO op name is collective/communication time."""
    return op_class(name).startswith(COMM_OP_PREFIXES)


# -- trace file discovery / loading ----------------------------------------


def find_trace_files(trace_dir: str) -> List[str]:
    """The ``*.trace.json.gz`` files of the NEWEST capture session under
    ``trace_dir`` (jax writes ``plugins/profile/<timestamp>/`` per
    ``stop_trace``; one file per host)."""
    sessions = sorted(
        d for d in glob.glob(os.path.join(trace_dir, "plugins", "profile", "*"))
        if os.path.isdir(d))
    if not sessions:
        return []
    newest = max(sessions, key=os.path.getmtime)
    return sorted(glob.glob(os.path.join(newest, "*.trace.json.gz")))


def load_trace_events(path: str) -> List[dict]:
    """All trace events from one gzipped Chrome-trace file."""
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    evs = data.get("traceEvents", []) if isinstance(data, dict) else []
    return [e for e in evs if isinstance(e, dict)]


# -- interval algebra -------------------------------------------------------


def _union(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Merge possibly-overlapping/nested (start, end) intervals."""
    if not intervals:
        return []
    out: List[Tuple[float, float]] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _measure(union: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in union)


def _intersection_measure(a: List[Tuple[float, float]],
                          b: List[Tuple[float, float]]) -> float:
    """Total overlap between two already-merged interval lists."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _async_base(cls: str) -> Optional[Tuple[str, str]]:
    """``('all-reduce', 'start'|'done')`` for an async-pair op class."""
    for side in ("start", "done"):
        if cls.endswith("-" + side):
            return cls[:-(len(side) + 1)], side
    return None


def _merge_async_pairs(comm_ev: Dict[Tuple, List[Tuple[float, float, str]]]
                       ) -> Dict[Tuple, List[Tuple[float, float]]]:
    """Comm events → per-lane intervals, with each async
    ``<op>-start``/``<op>-done`` pair merged into ONE interval spanning
    start-begin → done-end.

    Under XLA's latency-hiding scheduler the pair brackets one in-flight
    collective; counting the two ops as separate slivers mis-attributes
    it twice over: the in-flight window between them vanishes from
    ``comm_secs``, and when the runtime puts the halves on DIFFERENT
    lanes (a dedicated async-collective stream), the same collective is
    counted on both lanes — the done sliver then reads as fully exposed
    even while the start's lane is busy with the compute that hides it.
    Pairs are matched k-th-start ↔ k-th-done in timestamp order per op
    class, same-lane first, then across lanes (the merged interval lands
    on the START's lane — where the collective was issued, and where the
    compute that may hide it runs).  Unpaired halves and plain sync
    collectives keep their own intervals."""
    out: Dict[Tuple, List[Tuple[float, float]]] = {
        lane: [] for lane in comm_ev}
    # base op class -> side -> [(ts, end, lane)], ts-ordered
    leftovers: Dict[str, Dict[str, List[Tuple[float, float, Tuple]]]] = {}
    for lane, evs in comm_ev.items():
        by_base: Dict[str, Dict[str, List[Tuple[float, float]]]] = {}
        for ts, end, cls in evs:
            ab = _async_base(cls)
            if ab is None:
                out[lane].append((ts, end))
            else:
                by_base.setdefault(ab[0], {}).setdefault(
                    ab[1], []).append((ts, end))
        for base, sides in by_base.items():
            starts = sorted(sides.get("start", []))
            dones = sorted(sides.get("done", []))
            for (s0, s1), (d0, d1) in zip(starts, dones):
                out[lane].append((s0, max(s1, d1, d0)))
            for side, rest in (("start", starts[len(dones):]),
                               ("done", dones[len(starts):])):
                for ts, end in rest:
                    leftovers.setdefault(base, {}).setdefault(
                        side, []).append((ts, end, lane))
    # cross-lane pairing of the leftovers (start on the compute lane,
    # done on a dedicated async stream — or vice versa)
    for base, sides in leftovers.items():
        starts = sorted(sides.get("start", []))
        dones = sorted(sides.get("done", []))
        for (s0, s1, lane_s), (d0, d1, _lane_d) in zip(starts, dones):
            out[lane_s].append((s0, max(s1, d1, d0)))
        for ts, end, lane in starts[len(dones):] + dones[len(starts):]:
            out[lane].append((ts, end))
    return {lane: iv for lane, iv in out.items() if iv}


# -- attribution ------------------------------------------------------------


def attribute(events: Iterable[dict]) -> Dict[str, Any]:
    """Per-dispatch device-time breakdown from raw trace events.

    Returns a plain JSON-able dict: ``compute_secs`` / ``comm_secs`` /
    ``exposed_comm_secs`` / ``overlap_ratio`` / ``lanes`` totals, the
    per-``hlo_module`` breakdown, the top op classes by device time, and
    the host dispatch-anchor counts (``train_dispatches`` /
    ``exchange_dispatches``)."""
    # lane = (pid, tid); per lane the compute interval lists and the comm
    # EVENT lists (us; comm keeps the op class so async start/done pairs
    # can merge into one in-flight interval — see _merge_async_pairs)
    comm_ev: Dict[Tuple, List[Tuple[float, float, str]]] = {}
    comp_iv: Dict[Tuple, List[Tuple[float, float]]] = {}
    # module -> ("comm"|"compute") -> lane -> intervals/events: the
    # per-module breakdown keeps the lane split so device A's compute
    # can't masquerade as overlap for device B's collective
    per_module: Dict[str, Dict[str, Dict[Tuple, List]]] = {}
    op_totals: Dict[str, List[float]] = {}            # class -> [us, count]
    train_dispatches = 0
    exchange_dispatches = 0
    n_op_events = 0
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        if name == TRAIN_DISPATCH_SPAN:
            train_dispatches += 1
            continue
        if name == EXCHANGE_SPAN:
            exchange_dispatches += 1
            continue
        args = ev.get("args")
        if not isinstance(args, dict) or "hlo_op" not in args:
            continue                       # host python/runtime span
        try:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if dur < 0:
            continue
        n_op_events += 1
        # _src disambiguates per-host capture files merged by profile_dir:
        # two hosts' device planes reuse the same small pid/tid integers,
        # and merging them into one lane would let host A's compute mask
        # host B's collective as overlap
        lane = (ev.get("_src"), ev.get("pid"), ev.get("tid"))
        cls = op_class(name)
        comm = is_comm_op(name)
        if comm:
            comm_ev.setdefault(lane, []).append((ts, ts + dur, cls))
        else:
            comp_iv.setdefault(lane, []).append((ts, ts + dur))
        tot = op_totals.setdefault(cls, [0.0, 0])
        tot[0] += dur
        tot[1] += 1
        mod = str(args.get("hlo_module", "?"))
        m = per_module.setdefault(mod, {"comm": {}, "compute": {}})
        if comm:
            m["comm"].setdefault(lane, []).append((ts, ts + dur, cls))
        else:
            m["compute"].setdefault(lane, []).append((ts, ts + dur))

    def _breakdown(comm_events, comp_by_lane):
        comm_by_lane = _merge_async_pairs(comm_events)
        comm_us = comp_us = exposed_us = 0.0
        for lane in set(comm_by_lane) | set(comp_by_lane):
            cu = _union(comm_by_lane.get(lane, []))
            pu = _union(comp_by_lane.get(lane, []))
            c = _measure(cu)
            comm_us += c
            comp_us += _measure(pu)
            exposed_us += c - _intersection_measure(cu, pu)
        return comm_us, comp_us, exposed_us

    comm_us, comp_us, exposed_us = _breakdown(comm_ev, comp_iv)
    # bubble fraction: per compute lane, the dispatch window is that
    # lane's first-compute-start → last-compute-end; everything inside
    # it with NO compute running on the lane is bubble (pipeline
    # fill/drain gaps, microbatch waits, exposed same-lane collectives).
    # Span-weighted across lanes so a short-lived lane can't swamp the
    # verdict; None when the trace carries no compute.
    bubble_span_us = bubble_idle_us = 0.0
    for lane, ivs in comp_iv.items():
        u = _union(ivs)
        if not u:
            continue
        span = u[-1][1] - u[0][0]
        if span <= 0:
            continue
        bubble_span_us += span
        bubble_idle_us += span - _measure(u)
    bubble_fraction = round(bubble_idle_us / bubble_span_us, 4) \
        if bubble_span_us > 0 else None
    modules: Dict[str, dict] = {}
    for mod, m in per_module.items():
        mc, mp, mx = _breakdown(m["comm"], m["compute"])
        modules[mod] = {
            "comm_secs": round(mc / 1e6, 6),
            "compute_secs": round(mp / 1e6, 6),
            "exposed_comm_secs": round(mx / 1e6, 6),
        }
    top_ops = sorted(
        ({"op": cls, "secs": round(us / 1e6, 6), "count": n,
          "comm": is_comm_op(cls)}
         for cls, (us, n) in op_totals.items()),
        key=lambda r: -r["secs"])[:15]
    comm_secs = comm_us / 1e6
    exposed = exposed_us / 1e6
    return {
        "compute_secs": round(comp_us / 1e6, 6),
        "comm_secs": round(comm_secs, 6),
        "exposed_comm_secs": round(exposed, 6),
        "overlap_ratio": (round(1.0 - exposed / comm_secs, 4)
                          if comm_secs > 0 else None),
        "bubble_fraction": bubble_fraction,
        "lanes": len(set(comm_ev) | set(comp_iv)),
        # lanes that actually carry compute — the denominator for
        # per-device compute-busy time (a dedicated async collective
        # stream is a lane, but averaging compute over it would halve it)
        "compute_lanes": len(comp_iv),
        "n_op_events": n_op_events,
        "train_dispatches": train_dispatches,
        "exchange_dispatches": exchange_dispatches,
        "modules": modules,
        "top_ops": top_ops,
    }


def load_dir_events(trace_dir: str) -> List[dict]:
    """Raw trace events of the newest capture session under ``trace_dir``,
    merged across per-host files and ``_src``-tagged per file (the lane
    disambiguator ``attribute()``/``schedule_occupancy()`` expect).  Empty
    when no capture is found."""
    events: List[dict] = []
    for src, p in enumerate(find_trace_files(trace_dir)):
        try:
            file_events = load_trace_events(p)
        except (OSError, ValueError):
            continue          # a truncated capture file is not fatal
        for ev in file_events:
            ev["_src"] = src  # lane disambiguator (see attribute())
        events.extend(file_events)
    return events


def profile_dir(trace_dir: str) -> Optional[Dict[str, Any]]:
    """Parse the newest capture session under ``trace_dir`` into one
    attribution dict (events merged across per-host files).  None when no
    capture is found."""
    paths = find_trace_files(trace_dir)
    events = load_dir_events(trace_dir)
    if not events:
        return None
    prof = attribute(events)
    prof["trace_files"] = [os.path.basename(p) for p in paths]
    return prof


# -- schedule occupancy ------------------------------------------------------


def schedule_occupancy(events: Iterable[dict], min_gap_us: float = 1.0,
                       strip_width: int = 96) -> Dict[str, Any]:
    """Per-lane schedule occupancy: classify each compute lane's dispatch
    window into compute / hop / other-comm / idle time, per schedule slot.

    The pipeline scan runs one chunk of layers per tick, so a lane's
    merged compute intervals ARE its schedule slots — their count
    estimates the tick count, and the gaps between them are the
    schedule's bubble (warm-up/drain ticks a device spends cond-gated
    out, plus exposed hop waits).  ``hop`` time is ``collective-permute``
    device time (the stage-boundary activation shift); other collectives
    (psums etc.) classify as ``comm``.  Each lane also gets a ``strip``:
    ``strip_width`` equal time bins over the lane span, each rendered as
    the class owning the most time in the bin (``C`` compute, ``H`` hop,
    ``c`` other comm, ``·`` idle) — a schedule regression is a SHAPE you
    can read, not just a worse scalar.

    Gaps shorter than ``min_gap_us`` merge into the neighboring busy time
    (sub-microsecond runtime jitter is not schedule structure)."""
    comp_iv: Dict[Tuple, List[Tuple[float, float]]] = {}
    hop_iv: Dict[Tuple, List[Tuple[float, float]]] = {}
    comm_iv: Dict[Tuple, List[Tuple[float, float]]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args")
        if not isinstance(args, dict) or "hlo_op" not in args:
            continue
        try:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if dur < 0:
            continue
        lane = (ev.get("_src"), ev.get("pid"), ev.get("tid"))
        cls = op_class(ev.get("name", ""))
        if cls.startswith("collective-permute"):
            hop_iv.setdefault(lane, []).append((ts, ts + dur))
        elif is_comm_op(cls):
            comm_iv.setdefault(lane, []).append((ts, ts + dur))
        else:
            comp_iv.setdefault(lane, []).append((ts, ts + dur))

    def _clip(ivs, lo, hi):
        return [(max(s, lo), min(e, hi)) for s, e in ivs
                if min(e, hi) > max(s, lo)]

    def _measure_in(ivs, lo, hi):
        return _measure(_clip(ivs, lo, hi))

    lanes = []
    for lane in sorted(comp_iv, key=str):
        cu = _union(comp_iv[lane])
        # merge sub-min_gap_us jitter between compute slots
        merged: List[Tuple[float, float]] = []
        for s, e in cu:
            if merged and s - merged[-1][1] <= min_gap_us:
                merged[-1] = (merged[-1][0], e)
            else:
                merged.append((s, e))
        lo, hi = merged[0][0], merged[-1][1]
        span = hi - lo
        if span <= 0:
            continue
        hu = _union(hop_iv.get(lane, []))
        mu = _union(comm_iv.get(lane, []))
        comp_us = _measure(merged)
        # busy precedence compute > hop > comm: overlapped (hidden) hop
        # time is not a stall, so it must not double-count against idle
        hop_us = _measure_in(hu, lo, hi)
        comm_us = _measure_in(mu, lo, hi)
        busy = _union(_clip(merged + hu + mu, lo, hi))
        idle_us = span - _measure(busy)
        strip_chars = []
        for b in range(strip_width):
            blo = lo + span * b / strip_width
            bhi = lo + span * (b + 1) / strip_width
            shares = (("C", _measure_in(merged, blo, bhi)),
                      ("H", _measure_in(hu, blo, bhi)),
                      ("c", _measure_in(mu, blo, bhi)))
            best, best_us = "·", 0.0
            covered = 0.0
            for ch, us in shares:
                covered += us
                if us > best_us:
                    best, best_us = ch, us
            if (bhi - blo) - covered > best_us:
                best = "·"
            strip_chars.append(best)
        lanes.append({
            "lane": f"{lane[0]}:{lane[1]}/{lane[2]}",
            "span_secs": round(span / 1e6, 6),
            "compute_secs": round(comp_us / 1e6, 6),
            "hop_secs": round(hop_us / 1e6, 6),
            "comm_secs": round(comm_us / 1e6, 6),
            "idle_secs": round(idle_us / 1e6, 6),
            "bubble_fraction": round(idle_us / span, 4),
            "n_slots": len(merged),
            "strip": "".join(strip_chars),
        })
    spans = sum(l["span_secs"] for l in lanes)
    idles = sum(l["idle_secs"] for l in lanes)
    return {
        "lanes": lanes,
        "n_lanes": len(lanes),
        # span-weighted like attribute()'s bubble_fraction, but gap-merged
        # at min_gap_us — the schedule-structure view of the same metric
        "bubble_fraction": round(idles / spans, 4) if spans > 0 else None,
    }


def _schedule_busy_counts(pp: int, v: int, m: int) -> List[int]:
    """Busy-device count per pipeline tick — the ``real`` column sums of
    ``parallel.pipeline.build_schedule`` (device ``r`` is busy at tick ``t``
    iff ``0 <= t - r < v·m``), replicated here in pure python so this
    module stays stdlib-only (pinned equal to the jax-side table in
    ``tests/test_pipeline_schedule.py``)."""
    pp, v, m = int(pp), int(v), int(m)
    total = v * m
    ticks = total + pp - 1
    return [sum(1 for r in range(pp) if 0 <= t - r < total)
            for t in range(ticks)]


def pipeline_schedule_report(events: Iterable[dict], pp: int, v: int,
                             m: int, passes: int = 2) -> Dict[str, Any]:
    """Measured pipeline-bubble report from a trace capture.

    CPU device lanes are a shared thread pool (one Eigen pool serves every
    simulated device), so per-lane gaps cannot read the SPMD schedule —
    but the schedule's tick structure survives in the ``collective-permute``
    events: every tick each of the ``pp`` devices hops once, so sorted hop
    timestamps group into ticks by COUNT (exactly ``pp`` per tick,
    ``T = v·m+pp−1`` ticks per pass).  A traced train step contains a
    whole number of passes over the schedule — forward plus its scan
    transpose, with XLA free to add replay passes (remat/recompute); the
    per-tick idle sequence is a PALINDROME (ramp-up ``pp−1``, plateau,
    ramp-down), so every consecutive block of ``T`` groups weights
    identically whichever direction it ran — the report never needs to
    know the pass structure.  Each group's start-to-start gap is that
    tick's measured wall time; the schedule table says how many devices
    idle that tick.  Returns:

    - ``schedule_verified``: the hop-event count divides exactly into
      whole ``T·pp`` passes — the compiled program demonstrably runs the
      expected tick count (``v·m+pp−1``, not ``m+pp−1``).
    - ``bubble_fraction``: duration-weighted measured bubble
      ``Σ idle_frac(tick)·dur(tick) / Σ dur(tick)`` — what the schedule's
      idle actually costs in wall time.
    - ``bubble_fraction_ticks``: the analytic ``1 − v·m/T`` over the
      VERIFIED tick structure (``passes`` — expected passes per train
      step, forward + transpose — only scales ``steps_detected``).
    """
    pp, v, m = int(pp), int(v), int(m)
    hops: List[Tuple[float, float]] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args")
        if not isinstance(args, dict) or "hlo_op" not in args:
            continue
        cls = op_class(ev.get("name", ""))
        if not cls.startswith("collective-permute"):
            continue
        if cls.endswith("-done"):
            # async lowering emits start/done PAIRS per hop; count one
            # event per hop whichever form the backend lowered to
            continue
        try:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        hops.append((ts, ts + dur))
    hops.sort()
    busy = _schedule_busy_counts(pp, v, m)
    ticks_pass = len(busy)
    n_groups = len(hops) // pp
    report: Dict[str, Any] = {
        "pp": pp, "v": v, "m": m, "passes": passes,
        "n_hop_events": len(hops),
        "ticks_per_pass": ticks_pass,
        "measured_ticks": n_groups,
        "schedule_verified": bool(
            hops and len(hops) % (ticks_pass * pp) == 0),
    }
    if not n_groups:
        report.update(bubble_fraction=None, bubble_fraction_ticks=None,
                      passes_detected=0, steps_detected=0)
        return report
    report["passes_detected"] = round(n_groups / ticks_pass, 3)
    report["steps_detected"] = round(n_groups / ticks_pass / passes, 3)
    # idle fraction per tick position within one pass — a palindrome, so
    # the weighting is direction-agnostic and any replay passes XLA adds
    # (remat recompute of the forward under grad) align the same way
    idle_seq = [1.0 - b / pp for b in busy]
    starts = [hops[g * pp][0] for g in range(n_groups)]
    durs = [starts[g + 1] - starts[g] for g in range(n_groups - 1)]
    med = sorted(durs)[len(durs) // 2] if durs else 0.0
    durs.append(med)      # the capture's last tick has no successor
    wsum = dsum = 0.0
    for g, dur in enumerate(durs):
        # clip inter-pass host/dispatch gaps (a "tick" spanning a step
        # boundary) to the median so one gap can't swamp the weighting
        dur = min(dur, 10 * med) if med > 0 else dur
        wsum += idle_seq[g % ticks_pass] * dur
        dsum += dur
    report["bubble_fraction"] = round(wsum / dsum, 4) if dsum > 0 else None
    report["bubble_fraction_ticks"] = round(
        1.0 - (v * m) / ticks_pass, 4)
    return report


def format_schedule(occ: Dict[str, Any]) -> str:
    """Human-readable per-lane occupancy report (the ``--schedule`` view of
    ``scripts/profile_model.py``)."""
    lines = ["per-lane schedule occupancy "
             "(C compute · H hop · c comm · · idle):"]
    for l in occ.get("lanes", []):
        lines.append(
            f"  {l['lane']:<16} slots={l['n_slots']:<4} "
            f"span={l['span_secs'] * 1e3:8.2f}ms "
            f"compute={l['compute_secs'] * 1e3:8.2f}ms "
            f"hop={l['hop_secs'] * 1e3:7.2f}ms "
            f"idle={l['idle_secs'] * 1e3:7.2f}ms "
            f"bubble={l['bubble_fraction']:.4f}")
        lines.append(f"    |{l['strip']}|")
    bf = occ.get("bubble_fraction")
    lines.append(f"  span-weighted bubble_fraction: "
                 f"{bf if bf is not None else 'n/a'}")
    return "\n".join(lines)


# -- programmatic capture ---------------------------------------------------


class _Capture:
    """Result holder for :func:`capture` — ``.profile`` is populated when
    the context exits (None if the backend emitted no usable trace)."""

    def __init__(self, trace_dir: str):
        self.trace_dir = trace_dir
        self.profile: Optional[Dict[str, Any]] = None


class capture:
    """Context manager driving one programmatic profiler window::

        with devprof.capture("/tmp/trace") as cap:
            for i in range(3):
                step(i)
            jax.block_until_ready(state)     # caller drains BEFORE exit
        cap.profile["overlap_ratio"]

    The caller must block on the traced work before the context exits —
    ``stop_trace`` only sees spans that have already executed."""

    def __init__(self, trace_dir: Optional[str] = None):
        self._own_dir = trace_dir is None
        if trace_dir is None:
            import tempfile
            trace_dir = tempfile.mkdtemp(prefix="devprof_")
        self._cap = _Capture(trace_dir)

    def __enter__(self) -> _Capture:
        import jax
        jax.profiler.start_trace(self._cap.trace_dir)
        return self._cap

    def __exit__(self, exc_type, exc, tb) -> None:
        import jax
        jax.profiler.stop_trace()
        if exc_type is None:
            try:
                self._cap.profile = profile_dir(self._cap.trace_dir)
            except Exception:
                self._cap.profile = None    # attribution must never raise
                                            # into the training loop
        if self._own_dir:
            # anonymous capture: the caller only wants the attribution, so
            # the multi-MB .trace.json.gz files must not accumulate under
            # /tmp across bench rows (pass trace_dir to keep the raw
            # capture for Perfetto)
            import shutil
            shutil.rmtree(self._cap.trace_dir, ignore_errors=True)


# -- consumers --------------------------------------------------------------


def feed_telemetry(profile: Dict[str, Any], tm=None) -> None:
    """Record one attribution result into the PR 4 registry: the
    :data:`DEVICE_GAUGES` gauges plus one :data:`PROFILE_EVENT` stream
    event (scalars + top 3 op classes — bounded, JSONL-friendly).  The
    schema-drift checker drives this live and pins the gauge set."""
    if tm is None:
        from . import telemetry
        tm = telemetry.active()
    if not tm.enabled:
        return
    for gname, key in zip(DEVICE_GAUGES,
                          ("compute_secs", "comm_secs", "exposed_comm_secs",
                           "overlap_ratio", "lanes")):
        v = profile.get(key)
        if v is not None:
            tm.gauge(gname, float(v))
    tm.event(PROFILE_EVENT,
             compute_secs=profile.get("compute_secs"),
             comm_secs=profile.get("comm_secs"),
             exposed_comm_secs=profile.get("exposed_comm_secs"),
             overlap_ratio=profile.get("overlap_ratio"),
             lanes=profile.get("lanes"),
             train_dispatches=profile.get("train_dispatches"),
             top_ops=[o["op"] for o in profile.get("top_ops", [])[:3]])


def profile_row_fields(profile: Dict[str, Any],
                       total_flops: Optional[float] = None,
                       peak_flops: Optional[float] = None) -> Dict[str, Any]:
    """The bench-row columns (:data:`TRACE_ROW_COLUMNS`, all keys always
    present).  ``device_mfu`` is the trace-derived cross-check of the
    ``cost_analysis`` MFU column: ``total_flops`` (per-device flops over
    the WHOLE traced window) against one lane's compute-busy time —
    None when flops/peak are unknown or the trace saw no compute."""
    lanes = profile.get("compute_lanes") or profile.get("lanes") or 0
    compute = profile.get("compute_secs") or 0.0
    mfu = None
    if total_flops and peak_flops and lanes and compute > 0:
        per_lane_secs = compute / lanes
        mfu = round(float(total_flops) / per_lane_secs / float(peak_flops), 4)
        if not math.isfinite(mfu):
            mfu = None
    return {
        "overlap_ratio": profile.get("overlap_ratio"),
        "exposed_comm_secs": profile.get("exposed_comm_secs"),
        "device_compute_secs": profile.get("compute_secs"),
        "device_comm_secs": profile.get("comm_secs"),
        "device_mfu": mfu,
        "bubble_fraction": profile.get("bubble_fraction"),
    }


def update_state_report(model) -> Dict[str, Any]:
    """Per-chip update-plane memory (:data:`USHARD_ROW_COLUMNS`): what a
    chip actually holds for the optimizer state + exchanger extra, against
    the replicated-equivalent layout.

    Measured, not modeled, on the live boxed state: every boxed leaf is
    ``[n_workers, ...]`` sharded ``P(workers)``, so per-chip bytes ARE
    boxed bytes over worker count — for a sharded leaf the rows are the
    partition (chunk each), for a replicated leaf each row is one full
    copy.  The replicated-equivalent prices ``model._replicated_opt``
    (the pre-chunking optimizer, EMA included) eval_shaped on the full
    params plus the rule's FULL extra template — per-worker divergent
    state (error feedback) appears identically on both sides, so the
    shrink ratio isolates exactly the redundancy sharding removes.
    ``scripts/predict_scaling.py`` joins its analytic model against these
    columns; bench.py folds them into sharded/control rows."""
    import jax
    import numpy as np
    from ..parallel.mesh import WORKER_AXIS

    def tree_bytes(t) -> int:
        return int(sum(
            int(np.prod(np.shape(x)) or 1) * np.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(t)))

    n = int(model.mesh.shape[WORKER_AXIS])
    state = model.step_state
    assert state is not None, "update_state_report needs a compiled model"
    per_chip = (tree_bytes(state["opt_state"])
                + tree_bytes(state["extra"])) // n
    opt = getattr(model, "_replicated_opt", None) or model.opt
    full_opt = jax.eval_shape(opt.init, model.params)
    exch = model.exchanger
    full_extra = exch._extra_full_template() \
        if hasattr(exch, "_extra_full_template") \
        else exch.extra_state_template()
    replicated = tree_bytes(full_opt) + tree_bytes(full_extra)
    return {
        "update_state_bytes_per_chip": per_chip,
        "update_state_bytes_replicated": replicated,
        "update_state_shrink": (round(replicated / per_chip, 3)
                                if per_chip else None),
    }


def compress_traffic_model(strategy: str, n_elems: int, n_workers: int, *,
                           rank: int = 2, chunk: int = 8192,
                           k_c: Optional[int] = None,
                           leaf_shapes: Optional[list] = None
                           ) -> Optional[Dict[str, Any]]:
    """Analytic per-exchange HBM-traffic model for the compression
    pipelines — pure python, jax-free (scripts/predict_scaling.py joins it
    against measured rows without touching a backend).

    Accounting contract: XLA-op granularity with NO fusion credit — every
    jnp-level op in the strategy's exchange reads its operands and writes
    its result to HBM, fp32 = 4 bytes/elem.  That is an upper bound for
    what XLA's fuser actually emits from the unfused graph, and exact for
    the Pallas kernels (each kernel is one pass by construction), so the
    legacy/fused ratio is the *guaranteed-by-construction* shrink, not a
    measured one.  Stage lists name every counted op so the estimate is
    auditable.

    Returns ``None`` for strategies with no compression pipeline.
    """
    w = int(n_workers)

    def _total(stages):
        return float(sum(b for _, b in stages))

    if strategy == "onebit":
        # pad to the pack grid, like flatten_tree(pad_to_multiple_of=...)
        n = n_elems + (-n_elems) % 32768
        fn, pk = 4.0 * n, n / 8.0          # fp32 pass / packed buffer bytes
        legacy_enc = [
            ("add c = flat + state", 3 * fn),
            ("abs(c)", 2 * fn),
            ("mean reduce -> scale", fn),
            ("where(c==0, 1, c)", 2 * fn),
            ("sign", 2 * fn),
            ("scale * sign", 2 * fn),
            ("sub -> new_state", 3 * fn),
            ("pack_signs", fn + pk),
        ]
        legacy_dec = [
            ("unpack+weighted-sum", w * pk + fn),
            ("div /size -> mean", 2 * fn),
        ]
        fused_enc = [
            ("pack_signs_encode kernel", 2 * fn + pk + fn),
            ("mean reduce -> scale", fn),
            ("signed_residual kernel", fn + pk + fn),
        ]
        fused_dec = [
            ("unpack_signs_weighted_mean kernel", w * pk + fn),
        ]
    elif strategy == "topk":
        n = n_elems + (-n_elems) % chunk
        rows = n // chunk
        k = int(k_c or max(1, round(chunk * 0.01)))
        fn = 4.0 * n
        wire = 4.0 * rows * k              # bf16 val + int16 offset per slot
        legacy_enc = [
            ("add c = flat + state", 3 * fn),
            ("abs(c)", 2 * fn),
            ("top_k select", fn + 2 * wire),
            ("take_along_axis vals", fn + wire),
            ("bf16/int16 casts + residual", 3 * wire),
            ("scatter-set residual -> new_state", 3 * fn),
        ]
        legacy_dec = [
            ("zeros dense", fn),
            ("global-index arith", 3 * w * wire),
            ("serialized HBM scatter-add", 2 * fn + w * wire),
            ("div /size -> mean", 2 * fn),
        ]
        fused_enc = [
            ("topk_encode kernel", fn + fn + 2 * wire),
        ]
        fused_dec = [
            ("topk_decode kernel (VMEM expand + /size)", w * wire + fn),
        ]
    elif strategy.startswith("powersgd"):
        r = rank
        shapes = [s for s in (leaf_shapes or [])
                  if len(s) >= 2
                  and min(math.prod(s[:-1]), int(s[-1])) > 4 * r]
        if not shapes:
            return None
        fac = 4.0 * r * sum(math.prod(s[:-1]) + int(s[-1])
                            for s in shapes)   # both factors' fp32 bytes
        mats = 4.0 * sum(math.prod(s) for s in shapes)
        legacy_enc = [
            ("Mp = M + e (per leaf)", 3 * mats),
            ("factor matmuls", 2 * (mats + fac)),
            ("per-leaf staging pack (flatten/pad/concat)", 2 * fac),
            ("per-leaf psum staging copies", 2 * fac),
        ]
        legacy_dec = [
            ("qr + Mhat decode", mats + 2 * fac),
            ("residual e' = Mp - Mhat", 3 * mats),
        ]
        fused_enc = [
            ("Mp = M + e (per leaf)", 3 * mats),
            ("matmul_pack kernels (MXU -> staging)", 2 * (mats + fac)),
            ("stacked psum staging (one buffer)", 2 * fac),
        ]
        fused_dec = legacy_dec
    else:
        return None

    legacy = _total(legacy_enc) + _total(legacy_dec)
    fused = _total(fused_enc) + _total(fused_dec)
    return {
        "strategy": strategy,
        "n_workers": w,
        "stages": {"legacy_encode": legacy_enc, "legacy_decode": legacy_dec,
                   "fused_encode": fused_enc, "fused_decode": fused_dec},
        "compress_hbm_bytes_legacy": legacy,
        "compress_hbm_bytes_fused": fused,
        "compress_hbm_shrink": round(legacy / fused, 3),
        "compress_decode_shrink": round(_total(legacy_dec)
                                        / _total(fused_dec), 3),
    }


def compress_traffic_report(model) -> Optional[Dict[str, Any]]:
    """The :data:`COMPRESS_ROW_COLUMNS` bench columns for a live model —
    :func:`compress_traffic_model` fed from the model's actual strategy
    config and parameter count.  ``None`` when the exchange strategy has
    no compression pipeline; bench.py folds the columns into onebit/topk/
    powersgd rows next to the measured step time."""
    import jax
    strat = model.exchanger.strategy
    leaf_shapes = [tuple(getattr(l, "shape", ()) or ())
                   for l in jax.tree.leaves(model.params)]
    n_elems = sum(math.prod(s) if s else 1 for s in leaf_shapes)
    from ..parallel.mesh import WORKER_AXIS
    w = int(model.mesh.shape[WORKER_AXIS])
    kw: Dict[str, Any] = {}
    if strat.name == "topk":
        kw = {"chunk": strat.chunk, "k_c": strat._k_c()}
    elif strat.name.startswith("powersgd"):
        kw = {"rank": strat.rank, "leaf_shapes": leaf_shapes}
    m = compress_traffic_model(strat.name, n_elems, w, **kw)
    if m is None:
        return None
    return {c: m[c] for c in COMPRESS_ROW_COLUMNS}


def format_profile(profile: Dict[str, Any], top: int = 15) -> str:
    """Human-readable breakdown (profile_model.py / worker verbose)."""
    lines = [
        f"device time: compute {profile['compute_secs']:.4f}s  "
        f"comm {profile['comm_secs']:.4f}s  "
        f"exposed comm {profile['exposed_comm_secs']:.4f}s  "
        + (f"overlap {profile['overlap_ratio']:.1%}"
           if profile.get("overlap_ratio") is not None else "overlap n/a")
        + f"  ({profile['lanes']} lane(s), "
          f"{profile['n_op_events']} op events, "
          f"{profile['train_dispatches']} train dispatch(es))"]
    if profile.get("top_ops"):
        lines.append("top op classes by device time:")
        total = sum(o["secs"] for o in profile["top_ops"]) or 1.0
        for o in profile["top_ops"][:top]:
            tag = " [comm]" if o["comm"] else ""
            lines.append(f"  {o['secs'] * 1e3:9.2f} ms  "
                         f"{100 * o['secs'] / total:5.1f}%  x{o['count']:<5d} "
                         f"{o['op'][:90]}{tag}")
    return "\n".join(lines)
