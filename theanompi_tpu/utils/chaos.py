"""Chaos harness: scheduled/seeded SIGKILL / SIGSTOP / delay fault
injection.

Preemption tolerance that is only ever exercised by real preemptions is
untested code: this module rehearses host loss on the CPU venue by
injecting faults into running ranks mid-epoch — by explicit schedule
(``parse_schedule``) or reproducible seed (``seeded_schedule``) — so the
elastic runtime's reactions (``parallel/membership.py``) are gated on
convergence-to-accuracy under faults, not on hope (tests/test_chaos.py,
scripts/chaos_run.py).

Fault kinds (POSIX process targets via ``pid_of``; in-process targets via
``delay_hook``):

* ``kill``  — SIGKILL: the preemption event.  The supervisor must detect
  the death (process exit), emit ``worker_leave``, and respawn with
  backoff (``worker_join``).
* ``stop``  — SIGSTOP for ``duration`` seconds, then SIGCONT: the wedge /
  network-partition event.  Short stops read as stragglers; stops past
  the lease timeout read as deaths even though the process never exited —
  exactly the case exit-code supervision misses.
* ``delay`` — a straggler: ``delay_hook(target, duration)`` when given
  (in-process throttle), else a STOP/CONT pair of that duration.

Stdlib-only on purpose: the harness must import (and the schedule parse
must run) in jax-free tooling and in the lint CLI's no-backend process.
"""

from __future__ import annotations

import os
import random
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

FAULT_KINDS = ("kill", "stop", "delay")

# the injection-audit event kind (telemetry stream + Perfetto instant
# marker) — the chaos gate matches worker_leave/worker_join transitions
# against these
FAULT_EVENT = "fault_injected"


class Fault:
    """One scheduled fault: ``kind`` at ``at`` seconds (from harness start)
    against worker/rank ``target``, with ``duration`` for stop/delay."""

    __slots__ = ("kind", "at", "target", "duration", "applied", "error")

    def __init__(self, kind: str, at: float, target: int,
                 duration: float = 0.0):
        assert kind in FAULT_KINDS, \
            f"unknown fault kind {kind!r}; have {FAULT_KINDS}"
        self.kind = kind
        self.at = float(at)
        self.target = int(target)
        self.duration = float(duration)
        self.applied = False
        self.error: Optional[str] = None

    def __repr__(self):
        dur = f":{self.duration:g}s" if self.duration else ""
        return f"{self.kind}@{self.at:g}:w{self.target}{dur}"


def parse_schedule(spec: str) -> List[Fault]:
    """``"kill@8:1,stop@12:2:3.5,delay@15:0:0.5"`` →
    [Fault(kill, t=8, target=1), Fault(stop, t=12, target=2, 3.5s), ...].
    Grammar per entry: ``<kind>@<seconds>:<target>[:<duration>]``."""
    faults: List[Fault] = []
    for entry in str(spec).split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            kind, _, rest = entry.partition("@")
            parts = rest.split(":")
            at, target = float(parts[0]), int(parts[1])
            duration = float(parts[2]) if len(parts) > 2 else 0.0
        except (ValueError, IndexError):
            raise ValueError(
                f"bad fault entry {entry!r}: want "
                f"<kind>@<seconds>:<target>[:<duration>]") from None
        faults.append(Fault(kind, at, target, duration))
    return sorted(faults, key=lambda f: f.at)


def seeded_schedule(seed: int, targets: Sequence[int], n_faults: int = 2,
                    t_min: float = 5.0, t_max: float = 30.0,
                    kinds: Sequence[str] = ("kill",),
                    duration: float = 2.0) -> List[Fault]:
    """A reproducible random schedule: ``n_faults`` draws of (kind, time ∈
    [t_min, t_max], target ∈ targets) from one seed — the chaos gate's
    'random non-zero ranks mid-epoch' with replayable failures."""
    rng = random.Random(int(seed))
    targets = list(targets)
    assert targets, "seeded_schedule needs at least one target"
    faults = [Fault(rng.choice(list(kinds)),
                    rng.uniform(t_min, t_max),
                    rng.choice(targets),
                    duration)
              for _ in range(int(n_faults))]
    return sorted(faults, key=lambda f: f.at)


class ChaosMonkey(threading.Thread):
    """Apply a fault schedule against live workers from a daemon thread.

    ``pid_of(target) -> pid|None`` resolves the CURRENT pid (elastic
    workers change pids across respawns; None while a target is between
    lives — the fault is retried for ``grace_s`` then dropped with
    ``error='no-pid'``).  ``delay_hook(target, duration)`` services
    ``delay`` faults for in-process targets (SPMD ranks have no pid of
    their own).  Each applied fault emits one :data:`FAULT_EVENT`
    telemetry event — the audit trail the chaos gate matches
    ``worker_leave``/``worker_join`` transitions against."""

    def __init__(self, schedule: Sequence[Fault],
                 pid_of: Optional[Callable[[int], Optional[int]]] = None,
                 delay_hook: Optional[Callable[[int, float], None]] = None,
                 telemetry_=None, poll_s: float = 0.05,
                 grace_s: float = 10.0, t0: Optional[float] = None):
        super().__init__(daemon=True, name="chaos-monkey")
        self.schedule = sorted(schedule, key=lambda f: f.at)
        self.pid_of = pid_of
        self.delay_hook = delay_hook
        self.telemetry = telemetry_
        self.poll_s = float(poll_s)
        self.grace_s = float(grace_s)
        self.t0 = time.time() if t0 is None else float(t0)
        self._halt = threading.Event()
        self.applied: List[Fault] = []

    # -- application --------------------------------------------------------

    def _signal(self, pid: int, sig) -> None:
        os.kill(int(pid), sig)

    def _emit(self, fault: Fault, pid: Optional[int]) -> None:
        self.applied.append(fault)
        tm = self.telemetry
        if tm is not None and getattr(tm, "enabled", False):
            tm.event(FAULT_EVENT, kind=fault.kind, worker=fault.target,
                     pid=pid, at=round(fault.at, 2),
                     duration=fault.duration)
        print(f"chaos: injected {fault!r} (pid {pid})",
              file=sys.stderr, flush=True)

    def _apply(self, fault: Fault) -> bool:
        """True when the fault landed (or permanently failed)."""
        if fault.kind == "delay" and self.delay_hook is not None:
            self.delay_hook(fault.target, fault.duration)
            fault.applied = True
            self._emit(fault, None)
            return True
        pid = self.pid_of(fault.target) if self.pid_of else None
        if pid is None:
            if time.time() - self.t0 - fault.at > self.grace_s:
                fault.error = "no-pid"
                fault.applied = True      # dropped, but resolved
                return True
            return False                  # target between lives — retry
        try:
            if fault.kind == "kill":
                self._signal(pid, signal.SIGKILL)
            else:                         # stop / pid-targeted delay
                self._signal(pid, signal.SIGSTOP)

                def _cont(p=pid):
                    try:
                        self._signal(p, signal.SIGCONT)
                    except (ProcessLookupError, OSError):
                        pass              # supervisor killed it meanwhile
                t = threading.Timer(max(fault.duration, 0.01), _cont)
                t.daemon = True
                t.start()
        except (ProcessLookupError, OSError) as e:
            fault.error = repr(e)
        fault.applied = True
        self._emit(fault, pid)
        return True

    # -- thread loop --------------------------------------------------------

    def run(self) -> None:
        pending = list(self.schedule)
        while pending and not self._halt.is_set():
            now = time.time() - self.t0
            still: List[Fault] = []
            for f in pending:
                if f.at <= now:
                    if not self._apply(f):
                        still.append(f)
                else:
                    still.append(f)
            pending = still
            self._halt.wait(self.poll_s)

    def stop(self, join_timeout: float = 5.0) -> None:
        self._halt.set()
        if self.is_alive():
            self.join(timeout=join_timeout)


def find_child_pid(parent_pid: int, needle: str,
                   timeout_s: float = 60.0) -> Optional[int]:
    """Scan ``/proc`` for a child of ``parent_pid`` whose cmdline contains
    ``needle`` (the bench ``_reap`` idiom) — how the chaos harness targets
    the worker subprocess under ``launcher --supervise`` without the
    launcher's cooperation."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    ppid = int(f.read().split()[3])
                if ppid != int(parent_pid):
                    continue
                with open(f"/proc/{entry}/cmdline", "rb") as f:
                    cmd = f.read().replace(b"\0", b" ").decode(
                        errors="replace")
                if needle in cmd:
                    return int(entry)
            except (OSError, ValueError, IndexError):
                continue
        time.sleep(0.05)
    return None


def wait_for_file(path: str, timeout_s: float = 60.0,
                  predicate: Optional[Callable[[str], bool]] = None) -> bool:
    """Poll until ``path`` exists (and ``predicate(contents)`` holds, when
    given) — the mid-epoch synchronization chaos tests key faults off
    (e.g. 'first checkpoint committed')."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if os.path.exists(path):
            if predicate is None:
                return True
            try:
                with open(path) as f:
                    if predicate(f.read()):
                        return True
            except OSError:
                pass
        time.sleep(0.05)
    return False
