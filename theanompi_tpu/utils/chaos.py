"""Chaos harness: scheduled/seeded SIGKILL / SIGSTOP / delay fault
injection.

Preemption tolerance that is only ever exercised by real preemptions is
untested code: this module rehearses host loss on the CPU venue by
injecting faults into running ranks mid-epoch — by explicit schedule
(``parse_schedule``) or reproducible seed (``seeded_schedule``) — so the
elastic runtime's reactions (``parallel/membership.py``) are gated on
convergence-to-accuracy under faults, not on hope (tests/test_chaos.py,
scripts/chaos_run.py).

Fault kinds (POSIX process targets via ``pid_of``; in-process targets via
``delay_hook``):

* ``kill``  — SIGKILL: the preemption event.  The supervisor must detect
  the death (process exit), emit ``worker_leave``, and respawn with
  backoff (``worker_join``).
* ``stop``  — SIGSTOP for ``duration`` seconds, then SIGCONT: the wedge /
  network-partition event.  Short stops read as stragglers; stops past
  the lease timeout read as deaths even though the process never exited —
  exactly the case exit-code supervision misses.
* ``delay`` — a straggler: ``delay_hook(target, duration)`` when given
  (in-process throttle), else a STOP/CONT pair of that duration.
* ``corrupt`` — a parameter corruption that slipped PAST the wire CRC
  (a bad apply, a flipped bit in device memory): the monkey drops a
  ``corrupt_w<target>.json`` trigger under ``corrupt_dir`` and the
  target worker perturbs its own live parameters at its next exchange
  round.  The §25 numerics beacon must then raise
  ``replica_divergence`` within one beacon period
  (``fleetmon.FAULT_ALERT_COVERAGE``) — the detection this kind exists
  to prove.  The ``duration`` field carries the perturbation SCALE
  (0 = the 1e-3 default), not seconds.

Stdlib-only on purpose: the harness must import (and the schedule parse
must run) in jax-free tooling and in the lint CLI's no-backend process.

Round 17 (docs/design.md §18): window decisions go through the clock
seam (``utils/clock.py``) and every fault that actually LANDS is
appended to the run's :data:`REALIZED_SCHEDULE` log, so a live chaos run
can be replayed (:func:`schedule_from_realized`,
``chaos_run.py --faults-from``) or diffed against a simfleet rehearsal
of the same schedule.
"""

from __future__ import annotations

import json
import os
import random
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

try:
    from .clock import WALL
except ImportError:        # file-path load (jax-free tooling): absolute
    from theanompi_tpu.utils.clock import WALL

FAULT_KINDS = ("kill", "stop", "delay", "corrupt")

# wire-level fault kinds (round 14): applied by the ChaosProxy to framed
# center traffic instead of to processes.  ``at`` opens a fault WINDOW of
# ``duration`` seconds; ``target`` matches the client id stamped in each
# frame's idempotency token (-1 = every client).
#   net_drop      — frames silently discarded (client times out, retries)
#   net_delay     — each frame stalls NET_DELAY_PER_FRAME_S before forward
#   net_dup       — each frame forwarded TWICE (the dedup-window test)
#   net_corrupt   — one body byte flipped (CRC catches it; client retries)
#   net_partition — connections cut and new ones refused for the window
NET_FAULT_KINDS = ("net_drop", "net_delay", "net_dup", "net_corrupt",
                   "net_partition")
FAULT_KINDS = FAULT_KINDS + NET_FAULT_KINDS

# per-frame stall inside a net_delay window — one knob, not per-fault
# grammar (the window length already comes from the schedule)
NET_DELAY_PER_FRAME_S = 0.25

# the injection-audit event kind (telemetry stream + Perfetto instant
# marker) — the chaos gate matches worker_leave/worker_join transitions
# against these
FAULT_EVENT = "fault_injected"

#: Filename (under a run's record_dir) of the REALIZED fault schedule:
#: one JSON line per fault that actually landed, with wall + relative
#: timestamps and the resolved target.  What a chaos run can be replayed
#: or diffed from (:func:`schedule_from_realized`) — the scheduled list
#: says what was asked for; this file says what happened.
REALIZED_SCHEDULE = "chaos_realized.jsonl"


def fault_window_active(schedule: Sequence["Fault"], kind: str, worker,
                        now: float) -> bool:
    """THE window-membership rule: is a fault window of ``kind`` covering
    ``worker`` open at ``now`` (seconds relative to the schedule's t0)?
    ``target == -1`` covers every client; ``worker=None`` (identity not
    yet known) matches only the -1 windows.  Shared verbatim by the live
    :class:`ChaosProxy` and simfleet's simulated transport, so the
    simulator faults frames by the same rule the real proxy does."""
    for f in schedule:
        if f.kind != kind or not (f.at <= now <= f.at + f.duration):
            continue
        if f.target == -1 or (worker is not None
                              and int(f.target) == int(worker)):
            return True
    return False


def append_realized(path: Optional[str], doc: dict) -> None:
    """Append one realized-fault line (crash-tolerant: a full disk or
    unwritable dir must never kill the harness)."""
    if not path:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(doc, sort_keys=True) + "\n")
    except OSError:
        pass


def schedule_from_realized(path: str) -> List["Fault"]:
    """Rebuild a replayable schedule from a realized log: each non-errored
    line becomes a :class:`Fault` at its *relative* landing time — feed it
    back to a ChaosMonkey/ChaosProxy (``chaos_run.py --faults-from``) or
    diff it against a simulated one (simfleet's fidelity cross-check)."""
    faults: List[Fault] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if doc.get("error"):
                continue               # never landed — nothing to replay
            faults.append(Fault(str(doc["kind"]), float(doc["rel"]),
                                int(doc["target"]),
                                float(doc.get("duration", 0.0))))
    return sorted(faults, key=lambda f: f.at)


class Fault:
    """One scheduled fault: ``kind`` at ``at`` seconds (from harness start)
    against worker/rank ``target``, with ``duration`` for stop/delay."""

    __slots__ = ("kind", "at", "target", "duration", "applied", "error")

    def __init__(self, kind: str, at: float, target: int,
                 duration: float = 0.0):
        assert kind in FAULT_KINDS, \
            f"unknown fault kind {kind!r}; have {FAULT_KINDS}"
        self.kind = kind
        self.at = float(at)
        self.target = int(target)
        self.duration = float(duration)
        self.applied = False
        self.error: Optional[str] = None

    def __repr__(self):
        dur = f":{self.duration:g}s" if self.duration else ""
        return f"{self.kind}@{self.at:g}:w{self.target}{dur}"


def parse_schedule(spec: str) -> List[Fault]:
    """``"kill@8:1,stop@12:2:3.5,delay@15:0:0.5"`` →
    [Fault(kill, t=8, target=1), Fault(stop, t=12, target=2, 3.5s), ...].
    Grammar per entry: ``<kind>@<seconds>:<target>[:<duration>]``."""
    faults: List[Fault] = []
    for entry in str(spec).split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            kind, _, rest = entry.partition("@")
            parts = rest.split(":")
            at, target = float(parts[0]), int(parts[1])
            duration = float(parts[2]) if len(parts) > 2 else 0.0
        except (ValueError, IndexError):
            raise ValueError(
                f"bad fault entry {entry!r}: want "
                f"<kind>@<seconds>:<target>[:<duration>]") from None
        faults.append(Fault(kind, at, target, duration))
    return sorted(faults, key=lambda f: f.at)


def seeded_schedule(seed: int, targets: Sequence[int], n_faults: int = 2,
                    t_min: float = 5.0, t_max: float = 30.0,
                    kinds: Sequence[str] = ("kill",),
                    duration: float = 2.0) -> List[Fault]:
    """A reproducible random schedule: ``n_faults`` draws of (kind, time ∈
    [t_min, t_max], target ∈ targets) from one seed — the chaos gate's
    'random non-zero ranks mid-epoch' with replayable failures."""
    rng = random.Random(int(seed))
    targets = list(targets)
    assert targets, "seeded_schedule needs at least one target"
    faults = [Fault(rng.choice(list(kinds)),
                    rng.uniform(t_min, t_max),
                    rng.choice(targets),
                    duration)
              for _ in range(int(n_faults))]
    return sorted(faults, key=lambda f: f.at)


class ChaosMonkey(threading.Thread):
    """Apply a fault schedule against live workers from a daemon thread.

    ``pid_of(target) -> pid|None`` resolves the CURRENT pid (elastic
    workers change pids across respawns; None while a target is between
    lives — the fault is retried for ``grace_s`` then dropped with
    ``error='no-pid'``).  ``delay_hook(target, duration)`` services
    ``delay`` faults for in-process targets (SPMD ranks have no pid of
    their own).  Each applied fault emits one :data:`FAULT_EVENT`
    telemetry event — the audit trail the chaos gate matches
    ``worker_leave``/``worker_join`` transitions against."""

    def __init__(self, schedule: Sequence[Fault],
                 pid_of: Optional[Callable[[int], Optional[int]]] = None,
                 delay_hook: Optional[Callable[[int, float], None]] = None,
                 telemetry_=None, poll_s: float = 0.05,
                 grace_s: float = 10.0, t0: Optional[float] = None,
                 clock=None, realized_path: Optional[str] = None,
                 corrupt_dir: Optional[str] = None):
        super().__init__(daemon=True, name="chaos-monkey")
        # net_* faults are the ChaosProxy's job — a pid-targeted monkey
        # given a mixed schedule must not SIGSTOP a process because a
        # PARTITION was asked for
        self.schedule = sorted((f for f in schedule
                                if f.kind not in NET_FAULT_KINDS),
                               key=lambda f: f.at)
        self.pid_of = pid_of
        self.delay_hook = delay_hook
        self.telemetry = telemetry_
        self.poll_s = float(poll_s)
        self.grace_s = float(grace_s)
        self.clock = clock or WALL
        self.t0 = self.clock.now() if t0 is None else float(t0)
        self.realized_path = realized_path
        self.corrupt_dir = corrupt_dir
        self._halt = threading.Event()
        self.applied: List[Fault] = []

    # -- application --------------------------------------------------------

    def _signal(self, pid: int, sig) -> None:
        os.kill(int(pid), sig)

    def _emit(self, fault: Fault, pid: Optional[int]) -> None:
        self.applied.append(fault)
        now = self.clock.now()
        append_realized(self.realized_path, {
            "ts": round(now, 3), "rel": round(now - self.t0, 3),
            "kind": fault.kind, "target": fault.target,
            "duration": fault.duration, "pid": pid,
            "error": fault.error, "source": "monkey"})
        tm = self.telemetry
        if tm is not None and getattr(tm, "enabled", False):
            tm.event(FAULT_EVENT, kind=fault.kind, worker=fault.target,
                     pid=pid, at=round(fault.at, 2),
                     duration=fault.duration)
        print(f"chaos: injected {fault!r} (pid {pid})",
              file=sys.stderr, flush=True)

    def _apply(self, fault: Fault) -> bool:
        """True when the fault landed (or permanently failed)."""
        if fault.kind == "delay" and self.delay_hook is not None:
            self.delay_hook(fault.target, fault.duration)
            fault.applied = True
            self._emit(fault, None)
            return True
        if fault.kind == "corrupt":
            # no pid involved: the trigger file is consumed by the target
            # worker itself at its next exchange round (async_easgd polls
            # its chaos_dir) — corruption from the inside, past every CRC
            if not self.corrupt_dir:
                fault.error = "no-corrupt-dir"
                fault.applied = True
                self._emit(fault, None)
                return True
            scale = fault.duration if fault.duration > 0 else 1e-3
            path = os.path.join(self.corrupt_dir,
                                f"corrupt_w{fault.target}.json")
            try:
                os.makedirs(self.corrupt_dir, exist_ok=True)
                tmp = f"{path}.tmp{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump({"target": fault.target, "scale": scale}, f)
                os.replace(tmp, path)
            except OSError as e:
                fault.error = repr(e)
            fault.applied = True
            self._emit(fault, None)
            return True
        pid = self.pid_of(fault.target) if self.pid_of else None
        if pid is None:
            if self.clock.now() - self.t0 - fault.at > self.grace_s:
                fault.error = "no-pid"
                fault.applied = True      # dropped, but resolved
                now = self.clock.now()    # the realized log records the
                append_realized(self.realized_path, {   # drop too — a
                    "ts": round(now, 3),  # diff must see asked-but-missed
                    "rel": round(now - self.t0, 3), "kind": fault.kind,
                    "target": fault.target, "duration": fault.duration,
                    "pid": None, "error": "no-pid", "source": "monkey"})
                return True
            return False                  # target between lives — retry
        try:
            if fault.kind == "kill":
                self._signal(pid, signal.SIGKILL)
            else:                         # stop / pid-targeted delay
                self._signal(pid, signal.SIGSTOP)

                def _cont(p=pid):
                    try:
                        self._signal(p, signal.SIGCONT)
                    except (ProcessLookupError, OSError):
                        pass              # supervisor killed it meanwhile
                t = threading.Timer(max(fault.duration, 0.01), _cont)
                t.daemon = True
                t.start()
        except (ProcessLookupError, OSError) as e:
            fault.error = repr(e)
        fault.applied = True
        self._emit(fault, pid)
        return True

    # -- thread loop --------------------------------------------------------

    def run(self) -> None:
        pending = list(self.schedule)
        while pending and not self._halt.is_set():
            now = self.clock.now() - self.t0
            still: List[Fault] = []
            for f in pending:
                if f.at <= now:
                    if not self._apply(f):
                        still.append(f)
                else:
                    still.append(f)
            pending = still
            self._halt.wait(self.poll_s)

    def stop(self, join_timeout: float = 5.0) -> None:
        self._halt.set()
        if self.is_alive():
            self.join(timeout=join_timeout)


# -- wire-level chaos: the faulting proxy ------------------------------------

def _recvn(sock, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        c = sock.recv(min(n - got, 1 << 20))
        if not c:
            raise ConnectionError(f"closed ({got}/{n})")
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)


def _read_frame(sock):
    """One wire frame (parallel/wire.py framing: ``[4B hlen][4B header
    CRC][header JSON][4B blen][body]``) as ``(prefix_bytes, header_dict,
    body_bytes)`` — the proxy reassembles whole frames so faults hit
    MESSAGES, not arbitrary byte runs (a half-forwarded frame would just
    wedge both ends instead of exercising the retry/dedup machinery)."""
    import json as _json
    import struct as _struct
    hl = _recvn(sock, 4)
    (hlen,) = _struct.unpack("!I", hl)
    hcrc = _recvn(sock, 4)
    hb = _recvn(sock, hlen)
    bl = _recvn(sock, 4)
    (blen,) = _struct.unpack("!I", bl)
    body = _recvn(sock, blen) if blen else b""
    try:
        header = _json.loads(hb)
    except ValueError:
        header = {}
    return hl + hcrc + hb + bl, header, body


class ChaosProxy:
    """A faulting TCP proxy between wire clients and the center server.

    Sits on its own port; every client connection gets a paired upstream
    connection and two pump threads.  Client→server frames are read
    WHOLE and, while a scheduled fault window is active, dropped,
    delayed, duplicated (the extra reply is swallowed on the way back so
    the client's request/reply stream stays aligned — the DUPLICATE
    hits the server's dedup window, which is the point), or corrupted
    (one body byte flipped; the CRC catches it server-side).
    ``net_partition`` cuts matching connections and refuses new ones for
    the window.  Fault targets match the client id in each frame's
    idempotency token (``tok.w``; -1 = all).  Every window that opens
    emits one :data:`FAULT_EVENT` audit event.

    Stdlib-only like the rest of this module; schedules come from
    :func:`parse_schedule` / :func:`seeded_schedule` with the
    :data:`NET_FAULT_KINDS` kinds."""

    def __init__(self, upstream_addr: str, schedule: Sequence[Fault] = (),
                 listen_host: str = "127.0.0.1", listen_port: int = 0,
                 telemetry_=None, t0: Optional[float] = None,
                 poll_s: float = 0.05, clock=None,
                 realized_path: Optional[str] = None):
        import socket as _socket
        host, port = str(upstream_addr).rsplit(":", 1)
        self.upstream = (host, int(port))
        self.schedule = sorted((f for f in schedule
                                if f.kind in NET_FAULT_KINDS),
                               key=lambda f: f.at)
        self.listen_host = listen_host
        self.listen_port = int(listen_port)
        self.telemetry = telemetry_
        self.clock = clock or WALL
        self.t0 = self.clock.now() if t0 is None else float(t0)
        self.realized_path = realized_path
        self.poll_s = float(poll_s)
        self._socket = _socket
        self._halt = threading.Event()
        self._lock = threading.Lock()
        self._conns: list = []          # [{c, u, worker, pattern}]
        self._lsock = None
        self._threads: list = []
        self.applied: List[Fault] = []
        self.frames_faulted: Dict[str, int] = {}

    # -- schedule -----------------------------------------------------------

    def _active(self, kind: str, worker) -> bool:
        return fault_window_active(self.schedule, kind, worker,
                                   self.clock.now() - self.t0)

    def _emit(self, fault: Fault) -> None:
        fault.applied = True
        with self._lock:
            self.applied.append(fault)
        now = self.clock.now()
        append_realized(self.realized_path, {
            "ts": round(now, 3), "rel": round(now - self.t0, 3),
            "kind": fault.kind, "target": fault.target,
            "duration": fault.duration, "pid": None,
            "error": None, "source": "proxy"})
        tm = self.telemetry
        if tm is not None and getattr(tm, "enabled", False):
            tm.event(FAULT_EVENT, kind=fault.kind, worker=fault.target,
                     at=round(fault.at, 2), duration=fault.duration)
        print(f"chaos-proxy: window open {fault!r}",
              file=sys.stderr, flush=True)

    def _count(self, kind: str) -> None:
        with self._lock:
            self.frames_faulted[kind] = self.frames_faulted.get(kind, 0) + 1

    # -- pumps --------------------------------------------------------------

    def _pump_c2s(self, st) -> None:
        try:
            while not self._halt.is_set():
                prefix, header, body = _read_frame(st["c"])
                tok = header.get("tok") or {}
                w = tok.get("w")
                if w is not None:
                    # 'w3' (island clients) or a bare int — match on digits
                    ws = str(w)
                    st["worker"] = int(ws[1:]) if ws[:1] == "w" and \
                        ws[1:].isdigit() else (int(ws) if
                                               ws.lstrip("-").isdigit()
                                               else None)
                if self._active("net_partition", st["worker"]):
                    self._count("net_partition")
                    break                       # cut the connection
                if self._active("net_drop", st["worker"]):
                    self._count("net_drop")
                    continue                    # frame evaporates
                if self._active("net_delay", st["worker"]):
                    self._count("net_delay")
                    time.sleep(NET_DELAY_PER_FRAME_S)
                if self._active("net_corrupt", st["worker"]) and body:
                    self._count("net_corrupt")
                    bb = bytearray(body)
                    bb[len(bb) // 2] ^= 0xFF    # CRC will catch it
                    body = bytes(bb)
                dup = self._active("net_dup", st["worker"])
                with st["wlock"]:
                    st["pattern"].append(0)     # forward this reply
                    st["u"].sendall(prefix + body)
                    if dup:
                        self._count("net_dup")
                        st["pattern"].append(1)  # swallow the dup's reply
                        st["u"].sendall(prefix + body)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            self._close_pair(st)

    def _pump_s2c(self, st) -> None:
        try:
            while not self._halt.is_set():
                prefix, header, body = _read_frame(st["u"])
                with st["wlock"]:
                    swallow = st["pattern"].popleft() \
                        if st["pattern"] else 0
                if swallow:
                    continue        # the duplicate's reply — client never
                                    # sent that frame twice, so it must
                                    # never see two replies
                if self._active("net_partition", st["worker"]):
                    self._count("net_partition")
                    break
                st["c"].sendall(prefix + body)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            self._close_pair(st)

    def _close_pair(self, st) -> None:
        for k in ("c", "u"):
            try:
                st[k].close()
            except OSError:
                pass
        with self._lock:
            if st in self._conns:
                self._conns.remove(st)

    # -- accept / monitor loops ---------------------------------------------

    def _accept_loop(self) -> None:
        from collections import deque
        while not self._halt.is_set():
            try:
                c, _ = self._lsock.accept()
            except OSError:
                return
            if self._active("net_partition", None):
                # a global (target −1) partition refuses NEW connections
                # too; a worker-targeted one can't match here — the peer's
                # identity is unknown until its first frame
                self._count("net_partition")
                try:
                    c.close()
                except OSError:
                    pass
                continue
            try:
                u = self._socket.create_connection(self.upstream,
                                                   timeout=5.0)
            except OSError:
                try:
                    c.close()       # center down: the outage passes through
                except OSError:
                    pass
                continue
            st = {"c": c, "u": u, "worker": None, "pattern": deque(),
                  "wlock": threading.Lock()}
            with self._lock:
                self._conns.append(st)
            # pump threads are NOT retained: a chaos run's retry storms
            # open thousands of short-lived pairs, and nothing joins them
            # (stop() severs their sockets via _conns instead)
            for fn in (self._pump_c2s, self._pump_s2c):
                threading.Thread(target=fn, args=(st,), daemon=True).start()

    def _monitor_loop(self) -> None:
        pending = [f for f in self.schedule if not f.applied]
        while pending and not self._halt.is_set():
            now = self.clock.now() - self.t0
            still = []
            for f in pending:
                if f.at <= now:
                    self._emit(f)
                    if f.kind == "net_partition":
                        # cut EXISTING matching connections at window open
                        with self._lock:
                            conns = list(self._conns)
                        for st in conns:
                            if f.target == -1 or \
                                    st["worker"] == f.target:
                                self._close_pair(st)
                else:
                    still.append(f)
            pending = still
            self._halt.wait(self.poll_s)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> str:
        """Bind + serve; returns the ``host:port`` clients should dial."""
        self._lsock = self._socket.socket()
        self._lsock.setsockopt(self._socket.SOL_SOCKET,
                               self._socket.SO_REUSEADDR, 1)
        self._lsock.bind((self.listen_host, self.listen_port))
        self._lsock.listen(64)
        addr = self._lsock.getsockname()
        for fn in (self._accept_loop, self._monitor_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)
        return f"{addr[0]}:{addr[1]}"

    def stop(self) -> None:
        self._halt.set()
        if self._lsock is not None:
            # shutdown BEFORE close: on Linux a bare close() of a
            # listening socket does not reliably wake a thread blocked
            # in accept() — shutdown makes it raise immediately, which
            # the bounded join below depends on
            try:
                self._lsock.shutdown(self._socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._lsock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for st in conns:
            self._close_pair(st)
        # bounded join of the accept/monitor threads: the closed listen
        # socket unblocks accept() and the halt event ends the monitor
        # within poll_s, but without a join they can outlive stop() into
        # the caller's teardown (audit reads, a same-port proxy restart)
        # — tpulint daemon-discipline
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []


def find_child_pid(parent_pid: int, needle: str,
                   timeout_s: float = 60.0) -> Optional[int]:
    """Scan ``/proc`` for a child of ``parent_pid`` whose cmdline contains
    ``needle`` (the bench ``_reap`` idiom) — how the chaos harness targets
    the worker subprocess under ``launcher --supervise`` without the
    launcher's cooperation."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    ppid = int(f.read().split()[3])
                if ppid != int(parent_pid):
                    continue
                with open(f"/proc/{entry}/cmdline", "rb") as f:
                    cmd = f.read().replace(b"\0", b" ").decode(
                        errors="replace")
                if needle in cmd:
                    return int(entry)
            except (OSError, ValueError, IndexError):
                continue
        time.sleep(0.05)
    return None


def wait_for_file(path: str, timeout_s: float = 60.0,
                  predicate: Optional[Callable[[str], bool]] = None) -> bool:
    """Poll until ``path`` exists (and ``predicate(contents)`` holds, when
    given) — the mid-epoch synchronization chaos tests key faults off
    (e.g. 'first checkpoint committed')."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if os.path.exists(path):
            if predicate is None:
                return True
            try:
                with open(path) as f:
                    if predicate(f.read()):
                        return True
            except OSError:
                pass
        time.sleep(0.05)
    return False
