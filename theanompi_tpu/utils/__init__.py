"""Support libs: recorder, optimizers, buffer/serialization helpers,
checkpointing (reference: theanompi/lib/, SURVEY.md §2.10)."""
