"""Training sentry: catch a sick run before it burns a hardware window.

The round-5 postmortem pattern this exists for: a run keeps dispatching —
so the stall watchdog stays quiet — while the loss has gone NaN, spiked
off a cliff, or throughput has silently halved (a degraded tunnel
window, a straggling data producer, a bad LR resume).  Nothing notices
until a human reads the console hours later.  The sentry watches the
recorder's print-cadence records and raises a structured ``anomaly``
event + a flight-recorder dump the moment the run stops looking like a
training run:

* **nan_loss** — the printed cost is NaN/±inf;
* **loss_spike** — cost exceeds the rolling-window median by
  ``sentry_loss_spike`` × the window's median-absolute-deviation scale
  (robust: one spike can't poison its own baseline, and WGAN-style
  negative losses don't break a ratio test);
* **throughput_regression** — images/sec drops below
  ``sentry_tput_drop`` × the rolling median;
* **grad_overflow** — the numerics plane (utils/numerics, §25) reports
  nonfinite gradient entries or a non-finite gradient norm;
* **update_ratio_collapse** — the update-to-param ratio falls below the
  absolute ``sentry_ratio_floor`` while gradients are nonzero: the
  optimizer is applying nothing (a zeroed LR resume, a saturated scale);
* **replica_divergence** — the cross-rank consistency beacon reports a
  digest mismatch beyond ``sentry_divergence_eps`` between replicas the
  exchange rule declares bit-identical (BSP post-reduce params, the
  EASGD/ASGD center copy).

The numerics detectors run off :meth:`observe_numerics` (fed the
``numerics.host_report`` dict at the same print cadence) and honor
:meth:`notice_discontinuity` exactly like the throughput detector: the
first report after a val/ckpt/restore boundary may describe a
legitimately transient state (a ``center_restore`` rejoin pulls
‖w−c‖ and the beacon through a real discontinuity) and is neither
judged nor learned from.

Detection runs at print cadence only (never per step — zero hot-path
cost), emits :data:`ANOMALY_EVENT` events through the PR 4 telemetry
registry, and triggers the existing flight-recorder dump once per
anomaly kind (the trail of the N events leading INTO the anomaly is the
diagnosable part; repeat dumps would only overwrite it with the sick
steady-state).  The event schema is pinned by the tpulint schema-drift
checker (docs/design.md §13).

Config knobs (worker config): ``sentry`` (default on whenever telemetry
is enabled; ``false`` disables), ``sentry_loss_spike`` (default 6.0 MAD
multiples), ``sentry_tput_drop`` (default 0.4), ``sentry_window``
(records, default 16), ``sentry_min_records`` (arming threshold,
default 4).

Stdlib-only by contract — the lint CLI drives a live instance without a
jax backend.
"""

from __future__ import annotations

import math
from collections import deque
from statistics import median
from typing import Any, Dict, List, Optional, Tuple

ANOMALY_EVENT = "anomaly"
ANOMALY_KINDS = ("nan_loss", "loss_spike", "throughput_regression",
                 "grad_overflow", "update_ratio_collapse",
                 "replica_divergence")


class TrainingSentry:
    """Rolling-window anomaly detector over recorder print records."""

    def __init__(self, config: Optional[dict] = None, telemetry=None):
        config = config or {}
        if telemetry is None:
            from . import telemetry as telemetry_mod
            telemetry = telemetry_mod.active()
        self.telemetry = telemetry
        self.loss_spike_mads = float(config.get("sentry_loss_spike", 6.0))
        self.tput_drop_share = float(config.get("sentry_tput_drop", 0.4))
        self.window = max(2, int(config.get("sentry_window", 16)))
        self.min_records = max(2, int(config.get("sentry_min_records", 4)))
        self.verbose = bool(config.get("verbose", True))
        self._costs: deque = deque(maxlen=self.window)
        self._tputs: deque = deque(maxlen=self.window)
        self.ratio_floor = float(config.get("sentry_ratio_floor", 1e-12))
        self.divergence_eps = float(config.get("sentry_divergence_eps", 0.0))
        self.records_seen = 0
        self.anomalies: List[Tuple[str, Any]] = []      # (kind, iter)
        self._dumped: set = set()
        self._tput_discontinuity = False
        self._numerics_discontinuity = False
        self._numerics_last_iter: Optional[int] = None

    def notice_discontinuity(self) -> None:
        """The caller declares the next record's throughput unrepresentative
        — the recorder's images/sec is wall time since the LAST TRAIN
        print, so the first record after a validation pass / checkpoint /
        shuffle spans that dead time and would read as a regression.  The
        next record's throughput is neither judged nor learned from; loss
        detection is unaffected (cost has no wall-time denominator).  The
        numerics detectors honor the same boundary (the first report after
        it may describe a transient rejoin/restore state)."""
        self._tput_discontinuity = True
        self._numerics_discontinuity = True

    # -- detection ----------------------------------------------------------

    def _loss_spike(self, cost: float) -> Optional[Dict[str, float]]:
        if len(self._costs) < self.min_records:
            return None
        med = median(self._costs)
        # MAD scale with a floor: a flat window (MAD 0) must not turn
        # float noise into an anomaly, so the deviation also has to clear
        # 5% of the median's magnitude (or an absolute epsilon near zero)
        mad = median(abs(c - med) for c in self._costs)
        scale = max(mad, 0.05 * abs(med), 1e-6)
        threshold = med + self.loss_spike_mads * scale
        if cost > threshold:
            return {"cost": cost, "median": med, "threshold": threshold}
        return None

    def _tput_regression(self, ips: float) -> Optional[Dict[str, float]]:
        if len(self._tputs) < self.min_records:
            return None
        med = median(self._tputs)
        threshold = self.tput_drop_share * med
        if med > 0 and ips < threshold:
            return {"images_per_sec": ips, "median": med,
                    "threshold": threshold}
        return None

    def observe_record(self, rec: dict) -> Optional[str]:
        """Feed one ``print_train_info`` record; returns the anomaly kind
        raised (first match wins: a NaN loss is not ALSO a spike), or
        None for a healthy record."""
        self.records_seen += 1
        it = rec.get("iter")
        cost = rec.get("cost")
        ips = rec.get("images_per_sec")
        kind = None
        detail: Dict[str, Any] = {}
        if cost is not None:
            try:
                cost = float(cost)
            except (TypeError, ValueError):
                cost = None
        if cost is not None and not math.isfinite(cost):
            kind, detail = "nan_loss", {"cost": str(cost)}
        elif cost is not None:
            d = self._loss_spike(cost)
            if d is not None:
                kind, detail = "loss_spike", d
        tput_ok = isinstance(ips, (int, float)) and ips > 0 and \
            not self._tput_discontinuity
        self._tput_discontinuity = False
        if kind is None and tput_ok:
            d = self._tput_regression(float(ips))
            if d is not None:
                kind, detail = "throughput_regression", d
        # windows only learn from healthy, finite samples — an anomaly
        # must not drag its own detection baseline toward itself
        if kind is None:
            if cost is not None and math.isfinite(cost):
                self._costs.append(cost)
            if tput_ok:
                self._tputs.append(float(ips))
        if kind is not None:
            self._raise(kind, it, detail)
        return kind

    def observe_numerics(self, report: Optional[dict]) -> Optional[str]:
        """Feed one ``numerics.host_report`` dict (print cadence); returns
        the anomaly kind raised, first match wins — an overflow is not
        ALSO judged for divergence.  Detectors are absolute-threshold
        (no rolling baseline): a corrupted replica or a zeroed update is
        anomalous from the very first report, which is what lets the
        chaos/SIGTERM coverage tests assert deterministically."""
        if report is None:
            return None
        it = report.get("iter")
        # the aux is a latest-sample carry — the same sample can surface
        # under several print records at a sparse cadence; judge each
        # sampled step once
        if it is not None and it == self._numerics_last_iter:
            return None
        self._numerics_last_iter = it
        if self._numerics_discontinuity:
            # val/ckpt/restore boundary: a center_restore rejoin or a
            # checkpoint reload legitimately moves ‖w−c‖/the beacon —
            # the first report after it is neither judged nor learned from
            self._numerics_discontinuity = False
            return None
        grad_norm = float(report.get("grad_norm", 0.0))
        nonfinite = float(report.get("nonfinite", 0.0))
        kind: Optional[str] = None
        detail: Dict[str, Any] = {}
        if nonfinite > 0 or not math.isfinite(grad_norm):
            kind = "grad_overflow"
            detail = {"nonfinite": nonfinite, "grad_norm": str(grad_norm)}
        if kind is None:
            div = report.get("divergence")
            if div is not None and div > self.divergence_eps:
                kind = "replica_divergence"
                detail = {"divergence": str(div),
                          "threshold": self.divergence_eps}
        if kind is None:
            ratio = float(report.get("update_ratio", 1.0))
            if grad_norm > 0 and ratio < self.ratio_floor:
                kind = "update_ratio_collapse"
                detail = {"update_ratio": ratio,
                          "grad_norm": grad_norm,
                          "floor": self.ratio_floor}
        if kind is not None:
            self._raise(kind, it, detail)
        return kind

    # -- reaction -----------------------------------------------------------

    def _raise(self, kind: str, it, detail: Dict[str, Any]) -> None:
        self.anomalies.append((kind, it))
        tm = self.telemetry
        if tm.enabled:
            tm.event(ANOMALY_EVENT, kind=kind, iter=it, **detail)
            tm.counter("sentry.anomalies")
            tm.counter("sentry." + kind)
            if kind not in self._dumped:
                # one dump per kind: the ring holds the events leading INTO
                # the first occurrence — the diagnosable part; later
                # occurrences would overwrite it with the sick steady-state
                self._dumped.add(kind)
                tm.dump_flight(reason=f"sentry {kind} at iter {it}")
        if self.verbose:
            pretty = " ".join(f"{k}={v}" for k, v in detail.items())
            print(f"SENTRY: {kind} at iter {it} ({pretty})", flush=True)
