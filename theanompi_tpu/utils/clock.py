"""The clock seam: every host-side *decision* clock behind one interface.

The survivability plane (membership leases, wire retry deadlines, chaos
fault windows — docs/design.md §14–§15) makes its decisions by comparing
timestamps.  Until round 17 those comparisons read ``time.time()``
directly, which welds the logic to wall time: rehearsing a 1,000-worker
fault schedule then costs 1,000 processes and wall-clock minutes.  This
module is the seam that unwelds it (docs/design.md §18):

* :class:`Clock` — the two-method contract (``now()``/``sleep()``)
  decision logic is written against.
* :class:`WallClock` / :data:`WALL` — the default.  Real runs behave
  EXACTLY as before: ``now()`` is ``time.time()``, ``sleep()`` is
  ``time.sleep()``.
* ``theanompi_tpu.simfleet.clock.VirtualClock`` — the simulator's
  manually-advanced clock.  It lives in simfleet (utils must not import
  upward); only the interface is defined here.

Two rules keep the seam honest:

1. **Decision logic only.**  Telemetry event timestamps, log lines, and
   file mtimes stay on wall time — they describe when something really
   happened.  The clock seam covers times that are *compared*: lease
   freshness, backoff due-times, fault-window membership, retry
   deadlines.
2. **No host clocks in traced code.**  The seam is host-side
   orchestration; tpulint's trace-purity checker still forbids any
   ``now()`` (like any ``time.time()``) inside functions that flow into
   ``jax.jit``/``lax.scan``.

Stdlib-only: the chaos harness and the membership module import this in
jax-free tooling (lint probes, ``scripts/simfleet_run.py``).
"""

from __future__ import annotations

import time


class Clock:
    """The injectable time source.  ``now()`` returns seconds (an opaque,
    monotonically comparable epoch — wall seconds for :class:`WallClock`,
    virtual seconds for the simulator); ``sleep(dt)`` blocks the caller
    for ``dt`` of those seconds."""

    def now(self) -> float:
        raise NotImplementedError

    def sleep(self, dt: float) -> None:
        raise NotImplementedError


class WallClock(Clock):
    """Real time — the default everywhere, preserving pre-seam behavior
    bit for bit (``now`` IS ``time.time``)."""

    def now(self) -> float:
        return time.time()

    def sleep(self, dt: float) -> None:
        if dt > 0:
            time.sleep(dt)


#: The process-wide default.  ``clock or WALL`` is the idiom every
#: seam-carrying constructor uses, so passing ``clock=None`` (or nothing)
#: keeps wall-time semantics.
WALL = WallClock()
