"""Checkpoint / resume.

Reference behavior (SURVEY.md §5 "Checkpoint / resume"): per-epoch
``save_model`` dumped each layer's ``Weight`` to ``.npy`` files in a snapshot
dir; resume loaded them at model-build time via a config flag; optimizer
state was NOT saved.

This rebuild keeps the per-epoch cadence and the "load at build" flow but
checkpoints the FULL training state — params, optimizer state (velocity), BN
running stats, RNG key, epoch/step counters — as an ``.npz`` bundle plus the
reference-compatible per-leaf ``.npy`` params snapshot, so both resume paths
work.  Everything is host-side numpy: on multi-host, rank 0 saves (as the
reference did) since BSP state is replicated.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from . import helper_funcs


def save_checkpoint(ckpt_dir: str, step_state: Dict[str, Any], epoch: int,
                    count: int, keep_params_npy: bool = True) -> str:
    """``step_state`` is a dict of pytrees/scalars (params, opt_state, ...)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"ckpt_epoch{epoch}")
    flat: Dict[str, np.ndarray] = {}
    for key, tree in step_state.items():
        leaves, _ = jax.tree_util.tree_flatten(tree)
        for i, leaf in enumerate(leaves):
            flat[f"{key}__{i}"] = np.asarray(leaf)
    np.savez(path + ".npz", **flat)
    with open(path + ".json", "w") as f:
        json.dump({"epoch": epoch, "count": count,
                   "keys": sorted(step_state.keys())}, f)
    if keep_params_npy and "params" in step_state:
        helper_funcs.save_params(step_state["params"],
                                 os.path.join(ckpt_dir, f"params_epoch{epoch}"))
    _write_latest(ckpt_dir, epoch)
    return path + ".npz"


def load_checkpoint(ckpt_dir: str, template: Dict[str, Any],
                    epoch: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Restore state shaped like ``template``; returns None if no checkpoint."""
    if epoch is None:
        epoch = latest_epoch(ckpt_dir)
        if epoch is None:
            return None
    path = os.path.join(ckpt_dir, f"ckpt_epoch{epoch}.npz")
    if not os.path.exists(path):
        return None
    data = np.load(path)
    out: Dict[str, Any] = {}
    for key, tree in template.items():
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        new_leaves = []
        for i, leaf in enumerate(leaves):
            arr = data[f"{key}__{i}"]
            new_leaves.append(arr.astype(leaf.dtype) if hasattr(leaf, "dtype") else arr)
        out[key] = jax.tree_util.tree_unflatten(treedef, new_leaves)
    with open(os.path.join(ckpt_dir, f"ckpt_epoch{epoch}.json")) as f:
        meta = json.load(f)
    out["_meta"] = meta
    return out


def latest_epoch(ckpt_dir: str) -> Optional[int]:
    latest = os.path.join(ckpt_dir, "LATEST")
    if os.path.exists(latest):
        with open(latest) as f:
            return int(f.read().strip())
    if not os.path.isdir(ckpt_dir):
        return None
    epochs = [int(f[len("ckpt_epoch"):-4]) for f in os.listdir(ckpt_dir)
              if f.startswith("ckpt_epoch") and f.endswith(".npz")]
    return max(epochs) if epochs else None


def _write_latest(ckpt_dir: str, epoch: int) -> None:
    with open(os.path.join(ckpt_dir, "LATEST"), "w") as f:
        f.write(str(epoch))
