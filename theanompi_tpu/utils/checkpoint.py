"""Checkpoint / resume.

Reference behavior (SURVEY.md §5 "Checkpoint / resume"): per-epoch
``save_model`` dumped each layer's ``Weight`` to ``.npy`` files in a snapshot
dir; resume loaded them at model-build time via a config flag; optimizer
state was NOT saved.

This rebuild keeps the per-epoch cadence and the "load at build" flow but
checkpoints the FULL training state: the *boxed* ``[n_workers, ...]`` state
trees (params, optimizer state, BN stats, exchanger extras — so diverged
async-rule replicas and per-worker GoSGD α survive a resume), the training
and exchange PRNG keys, and the data cursor (shuffle seed + batch pointers +
augmentation RNG state), as an ``.npz`` bundle plus the reference-compatible
per-leaf ``.npy`` params snapshot.  Deterministic replay is therefore
bit-identical across a save/kill/resume boundary (tested in
``tests/test_checkpoint_and_data.py``).  Everything is host-side numpy: on
multi-host, rank 0 saves (as the reference did) after an all-gather of the
boxed state.

**Crash atomicity (round 13):** every artifact (``.npz``, ``.json``
sidecar, ``LATEST``) is written write-to-temp → fsync → ``os.replace``, so
a SIGKILL mid-save (preemption, the chaos harness, a supervisor kill)
leaves either the previous file or the new one — never a truncated zip.
On resume :func:`latest_epoch` additionally VALIDATES its candidate (zip
directory opens, sidecar parses) and falls back to the newest *valid*
checkpoint when the latest is damaged (pre-atomic checkpoints, torn NFS
writes), so ``--supervise``/elastic resume can never crash-loop on a
half-written file.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from . import helper_funcs


def _fsync_write(path: str, write_fn) -> None:
    """Crash-atomic file write: ``write_fn(fh)`` into ``path + '.tmp'``,
    fsync, then ``os.replace`` — a kill at ANY point leaves either the old
    complete file or the new complete file at ``path``."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        write_fn(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def checkpoint_valid(ckpt_dir: str, epoch: int) -> bool:
    """True when epoch's ``.npz`` opens as a complete zip AND the ``.json``
    sidecar parses — the resume-safety probe behind the newest-valid
    fallback.  A truncated archive (pre-atomic writer killed mid-save)
    fails the zip central-directory read here instead of deep inside
    ``load_checkpoint``."""
    base = os.path.join(ckpt_dir, f"ckpt_epoch{epoch}")
    try:
        with np.load(base + ".npz") as z:
            z.files          # forces the central-directory read
        with open(base + ".json") as f:
            json.load(f)
    except Exception:
        return False
    return True


def save_checkpoint(ckpt_dir: str, step_state: Dict[str, Any], epoch: int,
                    count: int, rng_keys: Optional[Dict[str, Any]] = None,
                    cursor: Optional[Dict[str, Any]] = None,
                    params_npy: Optional[Any] = None,
                    extra_meta: Optional[Dict[str, Any]] = None) -> str:
    """``step_state`` is a dict of pytrees (boxed or not — shapes round-trip
    through the ``template`` given to :func:`load_checkpoint`).

    ``rng_keys``: dict name → jax typed PRNG key; stored as raw key data plus
    the impl name, restored with ``jax.random.wrap_key_data``.
    ``cursor``: json-able scalars/strings plus numpy arrays (arrays go into
    the ``.npz``, the rest into the sidecar ``.json``).
    ``params_npy``: optional unboxed params pytree for the reference-style
    per-leaf ``.npy`` snapshot dir.
    """
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"ckpt_epoch{epoch}")
    flat: Dict[str, np.ndarray] = {}
    for key, tree in step_state.items():
        leaves, _ = jax.tree_util.tree_flatten(tree)
        for i, leaf in enumerate(leaves):
            flat[f"{key}__{i}"] = np.asarray(leaf)

    meta: Dict[str, Any] = {"epoch": epoch, "count": count,
                            "keys": sorted(step_state.keys())}
    if extra_meta:
        meta.update(extra_meta)
    if rng_keys:
        meta["rng_impl"] = {}
        for name, k in rng_keys.items():
            flat[f"_rngkey__{name}"] = np.asarray(jax.random.key_data(k))
            meta["rng_impl"][name] = str(jax.random.key_impl(k))
    if cursor:
        meta_cursor: Dict[str, Any] = {}
        for k, v in cursor.items():
            if isinstance(v, np.ndarray):
                flat[f"_cursor__{k}"] = v
            else:
                meta_cursor[k] = v
        meta["cursor"] = meta_cursor

    # arrays first, sidecar second, LATEST last — each step atomic, so the
    # commit point is the LATEST replace and a kill between steps leaves a
    # (possibly incomplete) epoch that latest_epoch's validity probe skips
    _fsync_write(path + ".npz", lambda f: np.savez(f, **flat))
    _fsync_write(path + ".json",
                 lambda f: f.write(json.dumps(meta).encode()))
    if params_npy is not None:
        helper_funcs.save_params(params_npy,
                                 os.path.join(ckpt_dir, f"params_epoch{epoch}"))
    _write_latest(ckpt_dir, epoch)
    return path + ".npz"


def load_checkpoint(ckpt_dir: str, template: Dict[str, Any],
                    epoch: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Restore state shaped like ``template``; returns None if no checkpoint.

    The returned dict carries the state trees plus ``_meta`` (the sidecar
    json), ``_rng_keys`` (name → wrapped typed key) and ``_cursor`` (merged
    scalar + array cursor entries) when those were saved.
    """
    if epoch is None:
        epoch = latest_epoch(ckpt_dir)
        if epoch is None:
            return None
    path = os.path.join(ckpt_dir, f"ckpt_epoch{epoch}.npz")
    if not os.path.exists(path):
        return None
    data = np.load(path)
    out: Dict[str, Any] = {}
    for key, tree in template.items():
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        new_leaves = []
        for i, leaf in enumerate(leaves):
            arr = data[f"{key}__{i}"]
            want = getattr(leaf, "shape", None)
            if want is not None and tuple(arr.shape) != tuple(want):
                raise ValueError(
                    f"incompatible checkpoint: '{key}' leaf {i} has shape "
                    f"{tuple(arr.shape)}, expected {tuple(want)} — the "
                    f"checkpoint was written by a different layout/worker "
                    f"count or an older format")
            new_leaves.append(arr.astype(leaf.dtype) if hasattr(leaf, "dtype") else arr)
        out[key] = jax.tree_util.tree_unflatten(treedef, new_leaves)
    with open(os.path.join(ckpt_dir, f"ckpt_epoch{epoch}.json")) as f:
        meta = json.load(f)
    out["_meta"] = meta
    if "rng_impl" in meta:
        out["_rng_keys"] = {
            name: jax.random.wrap_key_data(data[f"_rngkey__{name}"], impl=impl)
            for name, impl in meta["rng_impl"].items()}
    if "cursor" in meta:
        cursor = dict(meta["cursor"])
        prefix = "_cursor__"
        for k in data.files:
            if k.startswith(prefix):
                cursor[k[len(prefix):]] = data[k]
        out["_cursor"] = cursor
    return out


def peek_meta(ckpt_dir: str,
              epoch: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Read just the sidecar json (layout flags, epoch/count) — lets a loader
    shape its template before touching the arrays."""
    if epoch is None:
        epoch = latest_epoch(ckpt_dir)
        if epoch is None:
            return None
    path = os.path.join(ckpt_dir, f"ckpt_epoch{epoch}.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def latest_epoch(ckpt_dir: str) -> Optional[int]:
    """Newest *valid* epoch: the ``LATEST`` pointer when its checkpoint
    passes :func:`checkpoint_valid`, else a scan falling back through the
    on-disk epochs newest-first — a damaged latest checkpoint (SIGKILL
    mid-save under a pre-atomic writer) must never brick a supervised
    resume; it costs one epoch of progress instead."""
    candidates: list = []
    latest = os.path.join(ckpt_dir, "LATEST")
    if os.path.exists(latest):
        try:
            with open(latest) as f:
                candidates.append(int(f.read().strip()))
        except (ValueError, OSError):
            pass                  # torn pointer: fall through to the scan
    if os.path.isdir(ckpt_dir):
        epochs = [int(f[len("ckpt_epoch"):-4]) for f in os.listdir(ckpt_dir)
                  if f.startswith("ckpt_epoch") and f.endswith(".npz")]
        candidates.extend(sorted(epochs, reverse=True))
    seen = set()
    for ep in candidates:
        if ep in seen:
            continue
        seen.add(ep)
        if checkpoint_valid(ckpt_dir, ep):
            if candidates and ep != candidates[0]:
                import sys
                print(f"checkpoint: epoch {candidates[0]} is damaged/"
                      f"incomplete — resuming from newest valid epoch {ep}",
                      file=sys.stderr, flush=True)
            return ep
    return None


def _write_latest(ckpt_dir: str, epoch: int) -> None:
    _fsync_write(os.path.join(ckpt_dir, "LATEST"),
                 lambda f: f.write(str(epoch).encode()))
