"""Run-wide structured telemetry: metrics registry, event stream, flight
recorder.

The reference's entire observability story was the Recorder's four
wall-clock sums and a console print (the "time per 5120 images" tables);
at pod scale the questions that matter — which rank is the straggler, is
the prefetch queue starving, did HBM peak near OOM, what was a worker
doing in the 30 s before it hung — need a structured, run/rank-tagged
event stream and tooling that reads it across workers
(``scripts/telemetry_report.py``).

Three pieces, one process-wide instance (:func:`init` / :func:`active`):

* **Metrics registry** — named counters, gauges, and bounded-reservoir
  histograms (p50/p95/p99).  Fed by the Recorder's phase brackets
  (every ``recorder.end(section)`` lands one histogram sample AND one
  ``phase`` event), the PrefetchLoader's queue-depth/stall probes, the
  exchanger's per-exchange timings, and the compile cache's ladder
  counters.
* **Event stream** — each event is one JSONL line tagged with ``ts`` /
  ``run`` / ``rank``, appended to
  ``<record_dir>/telemetry_rank{r}.jsonl``.  On :meth:`Telemetry.close`
  a ``telemetry_summary_rank{r}.json`` sidecar lands next to it with the
  final counters/gauges/histogram summaries.
* **Flight recorder** — a bounded in-memory ring of the last N events
  (including ring-only watchdog heartbeats).  On crash, watchdog exit,
  or a fatal signal it is dumped to ``<record_dir>/flight_rank{r}.jsonl``;
  ``launcher.py --supervise`` sweeps per-rank dumps into a
  ``crash_<tag>/`` subdirectory before restarting, so a dead run leaves
  a diagnosable trail that the next attempt cannot overwrite.

**Cost contract**: telemetry is off unless the config enables it
(``record_dir`` set, or ``telemetry=true`` for an in-memory registry;
``telemetry=false`` force-disables).  Disabled, :func:`active` returns
the inert :data:`DISABLED` singleton whose ``enabled`` is ``False`` —
every hot-path call site guards with that ONE attribute check and skips
all telemetry work (``tests/test_telemetry.py`` pins the overhead).

This module imports no jax at module scope (scripts read it for
:data:`PHASES` without dragging a backend in); device probes import
lazily inside :meth:`Telemetry.system_snapshot`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# THE canonical phase list — single source of truth for
# ``recorder.SECTIONS``, the ``print_train_info`` record keys
# (``t_<phase>``), and the telemetry phase-event names
# (``phase`` events' ``sec`` field / ``phase.<name>`` histograms).
# The tpulint ``schema-drift`` checker (``scripts/lint.py``, run by
# ``scripts/tier1.sh``) fails the gate when any consumer drifts.
PHASES = ("compile", "train", "comm", "wait", "load", "stage", "val")

SCHEMA_VERSION = 1
FLIGHT_EVENTS = 256          # ring-buffer length (events, not bytes)


def host_rss_bytes() -> Optional[int]:
    """Resident set size of this process, or None when unknowable."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        try:
            import resource
            # ru_maxrss is KiB on linux (peak, not current — close enough
            # as the fallback)
            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            return None


def aggregate_memory_stats(stats: List[Optional[dict]]) -> Dict[str, int]:
    """Fold per-device ``memory_stats()`` dicts into the HBM gauge set.

    Device 0 alone hides single-host multi-chip pressure (one hot chip can
    OOM while device 0 reports headroom), so the aggregation is
    worst-case-oriented: bytes-in-use SUMS across devices (total HBM
    footprint), peak takes the MAX (the chip closest to OOM), the limit
    takes the per-device MIN (the binding budget — limits are uniform on
    real hardware, and when they aren't, the smallest one is the wall),
    and ``hbm_min_headroom_bytes`` is the worst single device's
    ``limit − peak``.  Devices reporting no stats (CPU sim) are skipped;
    empty input → empty dict (host gauges still emit)."""
    out: Dict[str, int] = {}
    ms = [m for m in stats if m]
    if not ms:
        return out
    in_use = [int(m["bytes_in_use"]) for m in ms if "bytes_in_use" in m]
    if in_use:
        out["hbm_bytes_in_use"] = sum(in_use)
    peaks = [int(m["peak_bytes_in_use"]) for m in ms
             if "peak_bytes_in_use" in m]
    if peaks:
        out["hbm_peak_bytes"] = max(peaks)
    limits = [int(m["bytes_limit"]) for m in ms if "bytes_limit" in m]
    if limits:
        out["hbm_bytes_limit"] = min(limits)
    headrooms = [int(m["bytes_limit"]) - int(m["peak_bytes_in_use"])
                 for m in ms
                 if "bytes_limit" in m and "peak_bytes_in_use" in m]
    if headrooms:
        out["hbm_min_headroom_bytes"] = min(headrooms)
    return out


class Histogram:
    """Bounded-reservoir histogram with exact count/sum/min/max.

    Samples are exact until ``cap``; past it the reservoir is thinned by
    keeping every other sample and doubling the record stride —
    systematic (deterministic) sampling, so tail percentiles stay
    representative while memory stays bounded."""

    __slots__ = ("count", "total", "min", "max", "_samples", "_stride",
                 "_skip", "_cap")

    def __init__(self, cap: int = 4096):
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._samples: List[float] = []
        self._cap = int(cap)
        self._stride = 1
        self._skip = 0

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.total += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        self._skip += 1
        if self._skip >= self._stride:
            self._skip = 0
            self._samples.append(v)
            if len(self._samples) >= self._cap:
                self._samples = self._samples[::2]
                self._stride *= 2

    def percentile(self, q: float) -> Optional[float]:
        if not self._samples:
            return None
        s = sorted(self._samples)
        idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
        return s[idx]

    def summary(self) -> dict:
        mean = self.total / self.count if self.count else None
        return {"count": self.count, "sum": round(self.total, 6),
                "min": self.min, "max": self.max,
                "mean": round(mean, 6) if mean is not None else None,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99)}


class Telemetry:
    """One process-wide (per-rank) registry + stream + flight ring.

    Thread-safe: the worker hot loop, the PrefetchLoader producer, and
    the watchdog monitor all feed it concurrently."""

    enabled = True

    def __init__(self, rank: int = 0, run_id: Optional[str] = None,
                 stream_dir: Optional[str] = None,
                 flight_events: int = FLIGHT_EVENTS, flush_every: int = 64):
        self.rank = int(rank)
        self.run_id = str(run_id) if run_id else \
            f"run{int(time.time())}p{os.getpid()}"
        self.stream_dir = stream_dir
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.hists: Dict[str, Histogram] = {}
        self._ring: deque = deque(maxlen=int(flight_events))
        # REENTRANT: the fatal-signal hook runs its dump on whatever thread
        # the signal lands on — if that thread was inside event() holding
        # the lock, a plain Lock would deadlock the dying process
        self._lock = threading.RLock()
        self._fh = None
        self._unflushed = 0
        self._flush_every = int(flush_every)
        if stream_dir:
            os.makedirs(stream_dir, exist_ok=True)
            # append: a supervised restart continues the same per-rank file
            # (events carry their own run id, so runs stay separable)
            self._fh = open(os.path.join(
                stream_dir, f"telemetry_rank{self.rank}.jsonl"), "a")
        self.event("run_start", schema=SCHEMA_VERSION, pid=os.getpid())

    # -- metrics ------------------------------------------------------------

    def counter(self, name: str, n: float = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = Histogram()
            h.observe(value)

    def phase(self, section: str, dt: float) -> None:
        """One recorder phase bracket: histogram sample + stream event.
        Event names/fields are part of the schema (docs/design.md §11)."""
        self.observe("phase." + section, dt)
        self.event("phase", sec=section, dt=round(dt, 6))

    # -- events -------------------------------------------------------------

    def event(self, name: str, /, ring_only: bool = False,
              **fields) -> None:
        # ``name`` is positional-ONLY so a field may also be called
        # "name" (the §17 span events carry one) without colliding
        ev = {"ts": round(time.time(), 3), "run": self.run_id,
              "rank": self.rank, "ev": name}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)
            if ring_only or self._fh is None:
                return
            try:
                self._fh.write(json.dumps(ev) + "\n")
                self._unflushed += 1
                if self._unflushed >= self._flush_every:
                    self._fh.flush()
                    self._unflushed = 0
            except (OSError, ValueError):
                pass            # telemetry must never fail the run

    def tail(self, n: int = 8) -> List[dict]:
        with self._lock:
            return list(self._ring)[-n:]

    # -- gauge snapshots ----------------------------------------------------

    def system_snapshot(self, **extra) -> dict:
        """Device memory aggregated over ALL local devices
        (:func:`aggregate_memory_stats`: summed bytes-in-use, max peak,
        min limit, worst-device headroom, plus ``device_count``), host
        RSS, the current prefetch queue depth (when the loader exports
        it), and caller extras (iteration rate, count) — recorded as
        gauges AND streamed as one ``gauges`` event."""
        vals = dict(extra)
        try:
            import jax
            devs = jax.local_devices()
            vals["device_count"] = len(devs)
            vals.update(aggregate_memory_stats(
                [d.memory_stats() for d in devs]))
        except Exception:
            pass                # CPU sims often have no memory_stats
        rss = host_rss_bytes()
        if rss:
            vals["host_rss_bytes"] = rss
        qd = self.gauges.get("prefetch.queue_depth")
        if qd is not None:
            # sampled into the stream here (the loader only sets the gauge
            # on its hot path) — telemetry_report's Perfetto export draws
            # its queue-depth counter track from these events
            vals["prefetch.queue_depth"] = qd
        for k, v in vals.items():
            if isinstance(v, (int, float)):
                self.gauge(k, v)
        self.event("gauges", **vals)
        return vals

    # -- summary / flight dump / lifecycle ----------------------------------

    def summary(self) -> dict:
        with self._lock:
            return {"run": self.run_id, "rank": self.rank,
                    "counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "hist": {k: h.summary() for k, h in self.hists.items()}}

    def dump_flight(self, reason: str = "",
                    dump_dir: Optional[str] = None) -> Optional[str]:
        """Write the ring buffer to ``flight_rank{r}.jsonl`` — the what-was-
        this-rank-doing trail for crash/stall post-mortems.  First line is a
        header with the reason; returns the path (None without a dir)."""
        d = dump_dir or self.stream_dir
        if not d:
            return None
        path = os.path.join(d, f"flight_rank{self.rank}.jsonl")
        try:
            os.makedirs(d, exist_ok=True)
            with self._lock:
                events = list(self._ring)
            with open(path, "w") as f:
                f.write(json.dumps(
                    {"ts": round(time.time(), 3), "run": self.run_id,
                     "rank": self.rank, "ev": "flight_dump",
                     "reason": str(reason)[:300],
                     "events": len(events)}) + "\n")
                for ev in events:
                    f.write(json.dumps(ev) + "\n")
        except OSError:
            return None
        return path

    def close(self) -> None:
        """Flush + close the stream and write the summary sidecar; the
        instance goes inert (``enabled=False``) so stale references left in
        other components after a re-:func:`init` become no-ops."""
        with self._lock:
            fh, self._fh = self._fh, None
        self.enabled = False
        if fh is not None:
            try:
                fh.flush()
                fh.close()
            except (OSError, ValueError):
                pass
        if self.stream_dir:
            try:
                with open(os.path.join(
                        self.stream_dir,
                        f"telemetry_summary_rank{self.rank}.json"),
                        "w") as f:
                    json.dump(self.summary(), f, indent=1, sort_keys=True)
            except OSError:
                pass


class _Disabled:
    """The inert registry: one attribute check (``enabled``) is the whole
    hot-path cost; every method is a no-op for call sites that don't
    guard."""

    enabled = False
    rank = 0
    run_id = None
    stream_dir = None
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Histogram] = {}

    def counter(self, name, n=1):
        pass

    def gauge(self, name, value):
        pass

    def observe(self, name, value):
        pass

    def phase(self, section, dt):
        pass

    def event(self, name, /, ring_only=False, **fields):
        pass

    def tail(self, n=8):
        return []

    def system_snapshot(self, **extra):
        return {}

    def summary(self):
        return {}

    def dump_flight(self, reason="", dump_dir=None):
        return None

    def close(self):
        pass


DISABLED = _Disabled()

_ACTIVE: Any = DISABLED


def active():
    """The process-wide registry — :data:`DISABLED` until :func:`init`
    enables one.  Components (prefetch, exchanger, compile cache,
    watchdog) read it lazily so no config threading is needed."""
    return _ACTIVE


def init(config: Optional[dict] = None):
    """(Re)initialize process-wide telemetry from a worker/model config.

    Enablement: ``telemetry=false`` force-disables; otherwise a
    ``record_dir`` enables the streaming registry (events land next to the
    recorder's inforec files), and ``telemetry=true`` without a dir
    enables an in-memory registry (metrics + flight ring, no stream —
    what bench.py uses).  A previous instance is closed first, so repeated
    in-process sessions don't leak file handles or cross-write streams."""
    global _ACTIVE
    config = config or {}
    t = config.get("telemetry", None)
    if t is False or (isinstance(t, str) and t.lower() == "false"):
        new: Any = DISABLED
    else:
        stream_dir = config.get("record_dir") or \
            (t if isinstance(t, str) else None)
        if t or stream_dir:
            new = Telemetry(rank=int(config.get("rank", 0)),
                            run_id=config.get("run_id"),
                            stream_dir=stream_dir,
                            flight_events=int(config.get(
                                "telemetry_flight_events", FLIGHT_EVENTS)),
                            # low-rate emitters that die by SIGKILL (the
                            # center process) flush eagerly so their
                            # span/audit tail survives the kill
                            flush_every=int(config.get(
                                "telemetry_flush_every", 64)))
        else:
            new = DISABLED
    old, _ACTIVE = _ACTIVE, new
    if old is not DISABLED and old is not new:
        old.close()
    return new


def install_signal_hooks(signals=None) -> None:
    """Dump the flight recorder on a fatal signal, then re-raise it with
    the default handler so the exit code stays honest.  Installed by the
    worker CLI entry only (never by the in-process session API — tests
    and host applications own their handlers).

    SIGTERM only by default: SIGINT must keep raising KeyboardInterrupt so
    the worker's unwind path runs (async-checkpoint flush in its finally
    block, flight dump in its except) — a kill-style handler there would
    skip both."""
    import signal as _signal
    sigs = signals or (_signal.SIGTERM,)

    def _handler(signum, frame):
        tm = active()
        if tm.enabled:
            tm.event("fatal_signal", signum=int(signum))
            tm.dump_flight(reason=f"signal {signum}")
            tm.close()
        _signal.signal(signum, _signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    for s in sigs:
        try:
            _signal.signal(s, _handler)
        except (ValueError, OSError):
            pass                # not the main thread / unsupported signal


def sweep_flight_dumps(record_dir: str, tag: str) -> Optional[str]:
    """Move per-rank ``flight_rank*.jsonl`` dumps into
    ``<record_dir>/crash_<tag>/`` — called by ``launcher.py`` after a
    supervised worker dies, so the restart's own eventual dumps cannot
    overwrite the trail that explains the death.  Returns the destination
    (None when there was nothing to sweep)."""
    import glob
    import shutil
    dumps = sorted(glob.glob(os.path.join(record_dir, "flight_rank*.jsonl")))
    if not dumps:
        return None
    dest = os.path.join(record_dir, f"crash_{tag}")
    os.makedirs(dest, exist_ok=True)
    for p in dumps:
        try:
            shutil.move(p, os.path.join(dest, os.path.basename(p)))
        except OSError:
            pass
    return dest
