"""Numerics health plane (docs/design.md §25).

The training stack can trace time (§17), watch fleet health (§20) and
attribute device cycles (§16), but none of that sees the *values* flowing
through training: a silently desynced BSP replica, a rejoined worker whose
``center_restore`` drifted, or a saturating error-feedback buffer all train
on undetected until the loss diverges.  This module closes that gap with
three pieces:

* **In-graph tensor statistics** — grad/param/update global L2 norm,
  max-abs, nonfinite count and update-to-param ratio, computed *inside*
  the compiled train step at a configurable ``numerics_every`` cadence
  (a ``lax.cond`` on the step count, the same pattern as the fused §8
  exchange cadence) and carried out of the dispatch as a small auxiliary
  pytree of per-worker f32 scalars.  Enabling them never adds a host
  round-trip: the host materializes the aux at print cadence, exactly
  when it already materializes cost/error.

* **Cross-rank consistency beacons** — a cheap dtype-stable float digest
  (per-leaf weighted f32 sums) of whatever tree the exchange rule declares
  bit-identical across workers (``Exchanger.numerics_extra``): the params
  under BSP grads mode, the center copy under EASGD/ASGD.  The boxed
  ``[n_workers]`` aux layout IS the all_gather — the host compares the
  per-rank digests and any bit-desync shows as ``divergence > 0`` within
  one beacon period.  Rules with genuinely divergent replicas and no
  replicated tree (GoSGD, BSP params mode between exchanges) mark the
  beacon invalid rather than alarm on healthy divergence.

* **The exact EASGD/ASGD distance** ``‖w_i − c‖`` — the central quantity
  of the source paper — plus the per-strategy EF-buffer/residual norm for
  the compressed wires (onebit/topk/powersgd).

The observer is provably inert: with ``numerics`` unset, every code path
in ``steps.build_train_step`` (and the compile-cache key) is byte-
identical to a build without this module; with it set, the stats read the
already-live values and change no update math (pinned per rule by
``tests/test_numerics.py``).

Module scope is stdlib-only (the §11 telemetry contract): the report/
record plane runs on machines with no jax; the traced helpers import jax
inside the function, at trace time.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

# -- schema -----------------------------------------------------------------

# Aux-pytree keys every sampled step carries out of the dispatch (per-worker
# f32 scalars; the host sees [n_workers] per key).  Fixed across rules —
# concepts a rule lacks read 0.0 with the matching validity flag down.
SAMPLE_KEYS = ("iter", "grad_norm", "grad_max_abs", "nonfinite",
               "param_norm", "update_norm", "update_ratio",
               "digest", "beacon", "dist_center", "ef_norm")

# Telemetry gauge vocabulary `record` emits under the one-`enabled`-check
# contract — the schema-drift checker probes live that every one of these
# lands in the registry.
NUMERICS_GAUGES = ("numerics.grad_norm", "numerics.grad_max_abs",
                   "numerics.nonfinite", "numerics.param_norm",
                   "numerics.update_norm", "numerics.update_ratio",
                   "numerics.divergence", "numerics.dist_center",
                   "numerics.ef_norm")

# Histograms (distributions across reports, p95/p99 in telemetry_report)
NUMERICS_HISTOGRAMS = ("numerics.grad_norm", "numerics.update_ratio")

# The event kind one report emits (telemetry_report TRACKED_EVENTS member;
# its numeric fields become Perfetto counter tracks)
NUMERICS_EVENT = "numerics"

# Sentry anomaly kinds the numerics detectors raise — must stay a subset
# of sentry.ANOMALY_KINDS (schema-drift-probed)
SENTRY_KINDS = ("grad_overflow", "update_ratio_collapse",
                "replica_divergence")

DEFAULT_EVERY = 1


def enabled(config) -> bool:
    """The ONE config gate: ``numerics=true``."""
    return bool((config or {}).get("numerics", False))


def cadence(config) -> int:
    return max(1, int((config or {}).get("numerics_every", DEFAULT_EVERY)))


def _leaf_weight(i: int) -> float:
    """Deterministic per-leaf digest weight in [0.5, 1.5): a Knuth-hash LCG
    on the leaf index, baked at trace time.  Distinct weights keep two
    leaves' corruptions from cancelling in the digest sum."""
    return 0.5 + ((i * 2654435761) % 65536) / 65536.0


def _sharded_axes(spec, group):
    """The group axes a PartitionSpec actually shards over (entries may be
    axis names or tuples of names)."""
    return tuple(a for e in (spec or ())
                 for a in (e if isinstance(e, (tuple, list)) else (e,))
                 if a in group)


# -- traced plane (jax imported at trace time only) -------------------------

class GraphPlan:
    """The traced numerics sampler for one ``build_train_step`` build.

    Constructed only when the plane is on (see :func:`graph_plan`);
    ``steps.build_train_step`` then threads ``compute``'s sample dict
    through the scan carry under ``lax.cond(count % every == 0, ...)``
    and adds one ``P(axis)`` out-spec per key — the off path never sees
    this class.
    """

    def __init__(self, model, exchanger, axis: str):
        self.model = model
        self.exchanger = exchanger
        self.axis = axis
        self.every = cadence(model.config)

    # group axes (model/pipe) a tp layout shards leaves over — worker-axis
    # stats psum over these so every rank reports the GLOBAL quantity
    def _group(self):
        return tuple(a for a in self.model.mesh.axis_names
                     if a != self.axis)

    def template(self):
        """The not-yet-sampled aux value: zeros with ``iter = -1`` (the
        host-side report treats a negative iter as 'no sample yet')."""
        import jax.numpy as jnp
        out = {k: jnp.float32(0.0) for k in SAMPLE_KEYS}
        out["iter"] = jnp.float32(-1.0)
        return out

    def _tree_sq(self, tree, pspecs):
        """Global Σx² over a params-shaped tree: per-leaf f32 square-sums,
        psum'd over the group axes a leaf's spec shards (replicated leaves
        counted once) — the same algebra as ``Exchanger._clip_grads``."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        group = self._group()

        def leaf_sq(x, spec=None):
            v = jnp.sum(jnp.square(x.astype(jnp.float32)))
            axes = _sharded_axes(spec, group) if spec is not None else ()
            return lax.psum(v, axes) if axes else v

        if pspecs is None or not group:
            return sum(leaf_sq(x) for x in jax.tree.leaves(tree))
        return sum(jax.tree.leaves(jax.tree.map(leaf_sq, tree, pspecs)))

    def _tree_nonfinite(self, tree, pspecs):
        import jax
        import jax.numpy as jnp
        from jax import lax
        group = self._group()

        def leaf_nf(x, spec=None):
            v = jnp.sum((~jnp.isfinite(x.astype(jnp.float32)))
                        .astype(jnp.float32))
            axes = _sharded_axes(spec, group) if spec is not None else ()
            return lax.psum(v, axes) if axes else v

        if pspecs is None or not group:
            return sum(leaf_nf(x) for x in jax.tree.leaves(tree))
        return sum(jax.tree.leaves(jax.tree.map(leaf_nf, tree, pspecs)))

    def _tree_max_abs(self, tree):
        """Global max|x|: local max then pmax over the group axes — max is
        idempotent over replicated leaves, so one unconditional pmax is
        correct for every layout."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        group = self._group()
        m = jnp.float32(0.0)
        for x in jax.tree.leaves(tree):
            m = jnp.maximum(m, jnp.max(jnp.abs(x.astype(jnp.float32))))
        return lax.pmax(m, group) if group else m

    def _digest(self, tree, pspecs):
        """Dtype-stable float digest: Σ_leaf w_i · Σ(leaf as f32), with the
        deterministic per-leaf weights.  Bit-identical replicas produce
        bitwise-equal digests (same values, same reduction order)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        group = self._group()

        def leaf_sum(x, spec=None):
            v = jnp.sum(x.astype(jnp.float32))
            axes = _sharded_axes(spec, group) if spec is not None else ()
            return lax.psum(v, axes) if axes else v

        if pspecs is None or not group:
            terms = [leaf_sum(x) for x in jax.tree.leaves(tree)]
        else:
            terms = jax.tree.leaves(jax.tree.map(leaf_sum, tree, pspecs))
        total = jnp.float32(0.0)
        for i, v in enumerate(terms):
            total = total + jnp.float32(_leaf_weight(i)) * v
        return total

    def compute(self, params_old, params_new, grads, extra, count):
        """One sample (dict over SAMPLE_KEYS of per-worker f32 scalars) —
        traced inside the step, under the caller's cadence ``cond``.  Pure
        reads of already-live values: touches no state, changes no update
        math (the inertness contract)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        pspecs = self.model.param_specs()
        group = self._group()
        tiny = jnp.float32(1e-30)

        grad_sq = self._tree_sq(grads, pspecs)
        param_sq = self._tree_sq(params_new, pspecs)
        upd = jax.tree.map(
            lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
            params_new, params_old)
        upd_sq = self._tree_sq(upd, pspecs)
        grad_norm = jnp.sqrt(grad_sq)
        param_norm = jnp.sqrt(param_sq)
        update_norm = jnp.sqrt(upd_sq)

        out = {
            "iter": jnp.asarray(count, jnp.float32),
            "grad_norm": grad_norm,
            "grad_max_abs": self._tree_max_abs(grads),
            "nonfinite": self._tree_nonfinite(grads, pspecs),
            "param_norm": param_norm,
            "update_norm": update_norm,
            "update_ratio": update_norm / jnp.maximum(param_norm, tiny),
            "digest": jnp.float32(0.0),
            "beacon": jnp.float32(0.0),
            "dist_center": jnp.float32(0.0),
            "ef_norm": jnp.float32(0.0),
        }
        nx = self.exchanger.numerics_extra(params_new, extra, self.axis)
        beacon_tree = nx.get("beacon_tree")
        if beacon_tree is not None:
            out["digest"] = self._digest(beacon_tree, pspecs)
            out["beacon"] = jnp.float32(1.0)
        center = nx.get("center")
        if center is not None:
            dist_sq = self._tree_sq(
                jax.tree.map(
                    lambda p, c: p.astype(jnp.float32)
                    - c.astype(jnp.float32), params_new, center), pspecs)
            out["dist_center"] = jnp.sqrt(dist_sq)
        ef = nx.get("ef_state")
        if ef is not None:
            # EF buffers are per-device divergent (each rank compresses its
            # own residual): the global norm sums every rank's local Σx²
            # over the group axes unconditionally
            sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                     for x in jax.tree.leaves(ef))
            if group:
                sq = lax.psum(sq, group)
            out["ef_norm"] = jnp.sqrt(sq)
        return out


def graph_plan(model, exchanger, axis: str) -> Optional[GraphPlan]:
    """The traced sampler when the plane is active for this build, else
    None — ``build_train_step``'s off path then never touches numerics.
    FSDP chunks have no params-shaped replica view inside the step; the
    plane stays off there (documented §25)."""
    if not enabled(getattr(model, "config", None)):
        return None
    if getattr(model, "_fsdp", None) is not None:
        return None
    return GraphPlan(model, exchanger, axis)


# -- host plane (stdlib only) -----------------------------------------------

def host_report(aux) -> Optional[Dict[str, Any]]:
    """Fold the device aux (dict of ``[n_workers]`` arrays, already
    ``device_get``'d) into one host report dict, or None while no sample
    has landed yet (``iter < 0``).

    Aggregation is worst-rank: max norms/ratios, summed nonfinite counts;
    ``divergence`` is ``max_i |digest_i − digest_0|`` over ranks whose
    beacon is valid (None when the rule declares no beacon)."""
    if aux is None:
        return None
    vals = {k: [float(x) for x in aux[k]] for k in SAMPLE_KEYS if k in aux}
    iters = vals.get("iter", [])
    if not iters or max(iters) < 0:
        return None
    n = len(iters)
    report: Dict[str, Any] = {
        "iter": int(max(iters)),
        "n_workers": n,
        "per_rank": vals,
        "grad_norm": max(vals["grad_norm"]),
        "grad_max_abs": max(vals["grad_max_abs"]),
        "nonfinite": sum(vals["nonfinite"]),
        "param_norm": max(vals["param_norm"]),
        "update_norm": max(vals["update_norm"]),
        "update_ratio": min(vals["update_ratio"]),
        "dist_center": max(vals["dist_center"]),
        "ef_norm": max(vals["ef_norm"]),
    }
    beacon = vals.get("beacon", [0.0] * n)
    digests = vals.get("digest", [0.0] * n)
    valid = [d for d, b in zip(digests, beacon) if b > 0]
    if len(valid) >= 2:
        # bitwise-equal replicas give exactly-equal digests; compare
        # against rank 0's so a single desynced rank shows as > 0.  A
        # non-finite digest is itself a divergence signal (a corrupted
        # replica whose params went inf/nan still must trip the beacon —
        # nan diffs would slip through a bare max()'s comparisons).
        ref = valid[0]
        diffs = [abs(d - ref) for d in valid]
        report["divergence"] = float("inf") if any(
            not math.isfinite(x) for x in diffs) else max(diffs)
    else:
        report["divergence"] = None
    return report


def example_report(n: int = 2) -> Dict[str, Any]:
    """A schema-complete healthy report (checker probes, tests)."""
    aux = {k: [0.0] * n for k in SAMPLE_KEYS}
    aux["iter"] = [1.0] * n
    aux["beacon"] = [1.0] * n
    aux["param_norm"] = [1.0] * n
    aux["grad_norm"] = [0.5] * n
    aux["update_norm"] = [0.01] * n
    aux["update_ratio"] = [0.01] * n
    return host_report(aux)


def record(tm, report, *, rank: Optional[int] = None) -> None:
    """Emit one report into telemetry: every NUMERICS_GAUGES gauge, the
    NUMERICS_HISTOGRAMS distributions, and one NUMERICS_EVENT carrying the
    numeric fields (the Perfetto counter tracks + flight-ring context).
    ONE ``enabled`` check guards the whole emission (§11 contract)."""
    if not tm.enabled or report is None:
        return
    div = report.get("divergence")
    gauges = {
        "numerics.grad_norm": report["grad_norm"],
        "numerics.grad_max_abs": report["grad_max_abs"],
        "numerics.nonfinite": report["nonfinite"],
        "numerics.param_norm": report["param_norm"],
        "numerics.update_norm": report["update_norm"],
        "numerics.update_ratio": report["update_ratio"],
        "numerics.divergence": 0.0 if div is None else div,
        "numerics.dist_center": report["dist_center"],
        "numerics.ef_norm": report["ef_norm"],
    }
    for name, value in gauges.items():
        tm.gauge(name, value)
    for name in NUMERICS_HISTOGRAMS:
        tm.observe(name, gauges[name])
    fields = {k: report[k] for k in ("iter", "grad_norm", "grad_max_abs",
                                     "nonfinite", "param_norm",
                                     "update_norm", "update_ratio",
                                     "dist_center", "ef_norm")}
    fields["divergence"] = div
    fields["beacon"] = int(div is not None)
    if rank is not None:
        fields["rank"] = rank
    tm.event(NUMERICS_EVENT, **fields)
