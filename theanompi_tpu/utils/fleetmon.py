"""Fleet health plane: streaming metrics aggregation, SLO rules, alerts.

Every fleet-level view before this round was post-hoc or point-in-time:
``scripts/telemetry_report.py`` merges per-rank JSONL after the run,
``statusz``/``fleetz`` answer one query per process.  Production-width
operation needs a *live* control room (docs/design.md §20):

* **Metric snapshots** — each long-lived process (worker, elastic
  island, center, supervisor) periodically samples its OWN telemetry
  registry (:func:`snapshot_from_telemetry`: phase p50/p99, img/s, HBM
  headroom, prefetch queue depth, wire rtt/outage, step count) and
  streams the sample over the §15 wire contract — a new
  :data:`METRICS_OP` request, idempotency-tokened and v2-framed like
  every other op — via a :class:`MetricStreamer` daemon thread.
* **:class:`FleetCollector`** — windowed fleet time series: per-rank
  bounded ring buffers per series plus fleet percentile rollups, a
  Prometheus-style text exposition (:meth:`FleetCollector.expose_text`),
  and the ``heartbeat_age_s`` series DERIVED from snapshot arrival times
  (the snapshot stream IS the health heartbeat: a killed or SIGSTOPped
  process stops streaming, and its age climbs with no cooperation from
  the dying side).
* **SLO rule engine** — declarative plain-dict rules (YAML-free; see
  :func:`validate_rules`): ``threshold`` / ``rate_of_change`` /
  ``sustained`` / ``fleet_quantile`` predicates over any series, scoped
  per-rank or fleet-wide.  Each breach episode fires EXACTLY one
  first-class :data:`ALERT_EVENT` telemetry event (no flapping: a firing
  rule stays silent until its condition clears, and a ``sustained``
  window must fill again before it can re-fire).
* **Alert-driven supervision** — rules carry an optional ``action``;
  :func:`apply_alert` feeds a per-rank ``demote`` alert into the
  EXISTING straggler-demotion path (``MembershipController.demote``)
  with the firing rule CITED in the ``worker_demote`` event, and the
  supervisor answers a fleet-wide ``flight_dump`` alert by asking every
  statusz endpoint to dump its flight ring (the §17 ``flight`` op).
* **Rehearsal + audit** — simfleet drives simulated metric streams
  through this REAL collector and rule engine in virtual time
  (``simfleet/health.py``), and :func:`audit_alerts` is the live chaos
  harness's closing check: every landed fault whose symptom a rule
  covers (:data:`FAULT_ALERT_COVERAGE`) must have produced its alert
  within one evaluation window.

**Cost contract** (§11): nothing here touches the training hot path.
The streamer is a low-rate daemon thread that only exists when
``metrics_addr`` is configured; every telemetry recording site in this
module guards on the ONE ``enabled`` attribute check (machine-checked —
the tpulint telemetry-hot-path pass knows this module's emission API).
Collector crash/restart rides the existing machinery: state snapshots
use the §14 crash-atomic write discipline and clients ride an outage on
§15 wire retries (the next interval's send simply retries).

Module scope is stdlib + the telemetry/clock shims — the tpulint
schema-drift checker loads this file jax-free to probe the alert/series
vocabulary live.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

try:
    from . import telemetry
    from .clock import WALL
except ImportError:        # file-path load (jax-free lint probe): absolute
    from theanompi_tpu.utils import telemetry
    from theanompi_tpu.utils.clock import WALL

#: The wire op a metric snapshot rides in on (idempotency-tokened by
#: ``WireClient`` like every mutating op — a retried snapshot is
#: deduplicated, never double-counted into the rings).
METRICS_OP = "metrics"

#: The alert event kind in the telemetry stream — consumed by
#: scripts/telemetry_report.py (Perfetto instant markers with the rule
#: name + firing value) and by the chaos alert-audit.
ALERT_EVENT = "alert"
ALERT_EVENTS = (ALERT_EVENT,)

#: Snapshot fields a process samples from its own registry — the metric
#: snapshot schema (docs/design.md §20).  All optional per sample (a
#: center has no prefetch queue); the collector keeps one ring per
#: (rank, field) that ever arrives.
METRIC_FIELDS = (
    "step_p50",              # phase.train histogram p50 (seconds)
    "step_p99",              # phase.train histogram p99 (seconds)
    "img_s",                 # images_per_sec gauge
    "hbm_headroom_bytes",    # hbm_min_headroom_bytes gauge
    "queue_depth",           # prefetch.queue_depth gauge
    "wire_rtt_p50",          # wire.rtt histogram p50 (seconds)
    "wire_rtt_p99",          # wire.rtt histogram p99 (seconds)
    "wire_outage_s",         # wire.outage_s gauge (last healed outage)
    "wire_retries",          # wire.retry counter (CUMULATIVE — the
                             # wire_degraded rule reads its rate, so a
                             # healed outage clears and a later fault
                             # re-alerts instead of latching forever)
    "steps",                 # heartbeat.iter gauge / caller extra
    "grad_norm",             # numerics.grad_norm gauge (§25 plane)
    "divergence",            # numerics.divergence gauge — the cross-rank
                             # beacon spread; nonzero means replicas that
                             # must agree bit-diverged
)

#: Series the collector maintains beyond the streamed fields — derived
#: at evaluation time, never sent.
DERIVED_SERIES = ("heartbeat_age_s",)

#: Every series name the collector can register — the exposition must
#: cover all of these (schema-drift-probed).
FLEET_SERIES = METRIC_FIELDS + DERIVED_SERIES

#: Counters the fleet-health machinery ticks (streamer side).
FLEETMON_COUNTERS = ("fleetmon.sent", "fleetmon.send_fail")

RULE_PREDICATES = ("threshold", "rate_of_change", "sustained",
                   "fleet_quantile")
RULE_OPS = (">", "<", ">=", "<=")
RULE_SCOPES = ("rank", "fleet")
RULE_ACTIONS = ("demote", "flight_dump")
#: The full key vocabulary one rule dict may carry.
RULE_KEYS = ("name", "series", "predicate", "op", "value", "window_s",
             "quantile", "scope", "action", "roles")

#: Which rule (by name) covers each chaos fault kind's SYMPTOM — the
#: contract :func:`audit_alerts` checks a live run against.  A fault
#: kind absent here has no collector-visible symptom contract: net_dup /
#: net_corrupt are absorbed by the dedup/CRC machinery by design, and a
#: ``kill`` under supervision is HEALED (detect + backoff respawn)
#: faster than any sane heartbeat threshold — its audit is the
#: leave→rejoin pair the chaos gate already matches; the health plane
#: only sees a kill when respawns exhaust and the silence grows, which
#: the heartbeat rule then catches as a bonus, not a contract.
FAULT_ALERT_COVERAGE = {
    "stop": ("heartbeat_lost",),
    "delay": ("step_time_degraded",),
    "net_partition": ("wire_degraded",),
    "net_drop": ("wire_degraded",),
    # a parameter corruption that slips PAST the wire CRC (net_corrupt
    # stays absorbed by design — this kind models the bad apply itself):
    # the numerics beacon must catch the resulting replica desync
    "corrupt": ("replica_divergence",),
}


def default_rules(heartbeat_s: float = 10.0,
                  step_p99_s: Optional[float] = None,
                  step_window_s: float = 10.0,
                  hbm_headroom_bytes: Optional[float] = None,
                  wire_retry_rate: float = 0.05,
                  wire_window_s: float = 5.0,
                  queue_starved_window_s: float = 10.0,
                  divergence: Optional[float] = None) -> List[dict]:
    """The stock rule set.  ``step_p99_s``/``hbm_headroom_bytes``/
    ``divergence`` default to None = rule omitted (absolute step-time and
    HBM budgets are workload-specific, and the divergence rule only means
    something when the §25 numerics beacon streams; the heartbeat/retry/
    queue rules are not).  The wire rule is rate-of-change over the
    CUMULATIVE retry counter deliberately: a latched last-outage gauge
    would fire once and never clear, so a second fault could never
    re-alert."""
    rules = [
        {"name": "heartbeat_lost", "series": "heartbeat_age_s",
         "predicate": "threshold", "op": ">", "value": float(heartbeat_s),
         "scope": "rank", "action": "demote", "roles": ("worker",)},
        {"name": "wire_degraded", "series": "wire_retries",
         "predicate": "rate_of_change", "op": ">",
         "value": float(wire_retry_rate),
         "window_s": float(wire_window_s), "scope": "rank",
         "roles": ("worker",)},
        {"name": "queue_starved", "series": "queue_depth",
         "predicate": "fleet_quantile", "quantile": 0.5, "op": "<",
         "value": 1.0, "window_s": float(queue_starved_window_s),
         "scope": "fleet", "action": "flight_dump", "roles": ("worker",)},
    ]
    if step_p99_s is not None:
        rules.append(
            {"name": "step_time_degraded", "series": "step_p99",
             "predicate": "sustained", "op": ">",
             "value": float(step_p99_s), "window_s": float(step_window_s),
             "scope": "rank", "action": "demote", "roles": ("worker",)})
    if hbm_headroom_bytes is not None:
        rules.append(
            {"name": "hbm_low_headroom", "series": "hbm_headroom_bytes",
             "predicate": "threshold", "op": "<",
             "value": float(hbm_headroom_bytes), "scope": "rank",
             "roles": ("worker",)})
    if divergence is not None:
        # threshold, not sustained: a single breaching beacon sample IS
        # the symptom — bit-desync never heals on its own, and the §25
        # acceptance bound is one beacon period, not a sustain window
        rules.append(
            {"name": "replica_divergence", "series": "divergence",
             "predicate": "threshold", "op": ">",
             "value": float(divergence), "scope": "rank",
             "action": "flight_dump", "roles": ("worker",)})
    return rules


DEFAULT_RULES = default_rules()


def validate_rules(rules: Sequence[dict]) -> List[dict]:
    """Check a rule list against the predicate grammar (docs/design.md
    §20); raises ``ValueError`` naming the offending rule/key.  Returns
    the rules unchanged so call sites can validate inline."""
    names = set()
    for r in rules:
        name = r.get("name")
        if not name or not isinstance(name, str):
            raise ValueError(f"rule without a name: {r!r}")
        if name in names:
            raise ValueError(f"duplicate rule name {name!r}")
        names.add(name)
        unknown = sorted(set(r) - set(RULE_KEYS))
        if unknown:
            raise ValueError(f"rule {name!r}: unknown key(s) {unknown} "
                             f"(have {RULE_KEYS})")
        if r.get("series") not in FLEET_SERIES:
            raise ValueError(f"rule {name!r}: unknown series "
                             f"{r.get('series')!r} (have {FLEET_SERIES})")
        pred = r.get("predicate")
        if pred not in RULE_PREDICATES:
            raise ValueError(f"rule {name!r}: unknown predicate {pred!r} "
                             f"(have {RULE_PREDICATES})")
        if r.get("op", ">") not in RULE_OPS:
            raise ValueError(f"rule {name!r}: unknown op {r.get('op')!r}")
        if "value" not in r:
            raise ValueError(f"rule {name!r}: no threshold value")
        if pred in ("sustained", "rate_of_change") and \
                float(r.get("window_s", 0)) <= 0:
            raise ValueError(f"rule {name!r}: predicate {pred!r} needs a "
                             f"positive window_s")
        if pred == "fleet_quantile" and \
                not (0.0 <= float(r.get("quantile", -1)) <= 1.0):
            raise ValueError(f"rule {name!r}: fleet_quantile needs "
                             f"quantile in [0, 1]")
        if r.get("scope", "fleet" if pred == "fleet_quantile"
                 else "rank") not in RULE_SCOPES:
            raise ValueError(f"rule {name!r}: unknown scope "
                             f"{r.get('scope')!r}")
        act = r.get("action")
        if act is not None and act not in RULE_ACTIONS:
            raise ValueError(f"rule {name!r}: unknown action {act!r} "
                             f"(have {RULE_ACTIONS})")
    return list(rules)


def _cmp(op: str, value: float, threshold: float) -> bool:
    if op == ">":
        return value > threshold
    if op == "<":
        return value < threshold
    if op == ">=":
        return value >= threshold
    return value <= threshold


# -- emission side ------------------------------------------------------------

def snapshot_from_telemetry(tm, **extra) -> Dict[str, float]:
    """One metric snapshot sampled from a live registry — the
    :data:`METRIC_FIELDS` subset this process can answer.  Cheap (reads
    state other paths already maintain; the two histogram percentiles
    sort bounded reservoirs) and NEVER called on the training hot path —
    only from the streamer's own low-rate thread."""
    out: Dict[str, float] = {}
    if not tm.enabled:
        return out
    h = tm.hists.get("phase.train")
    if h is not None and h.count:
        p50, p99 = h.percentile(50), h.percentile(99)
        if p50 is not None:
            out["step_p50"] = round(p50, 6)
        if p99 is not None:
            out["step_p99"] = round(p99, 6)
    rtt = tm.hists.get("wire.rtt")
    if rtt is not None and rtt.count:
        p50, p99 = rtt.percentile(50), rtt.percentile(99)
        if p50 is not None:
            out["wire_rtt_p50"] = round(p50, 6)
        if p99 is not None:
            out["wire_rtt_p99"] = round(p99, 6)
    for field, gauge in (("img_s", "images_per_sec"),
                         ("hbm_headroom_bytes", "hbm_min_headroom_bytes"),
                         ("queue_depth", "prefetch.queue_depth"),
                         ("wire_outage_s", "wire.outage_s"),
                         ("steps", "heartbeat.iter"),
                         ("grad_norm", "numerics.grad_norm"),
                         ("divergence", "numerics.divergence")):
        v = tm.gauges.get(gauge)
        if v is not None:
            out[field] = float(v)
    # cumulative, ALWAYS present once a wire client exists: the
    # wire_degraded rate rule needs steady baseline samples to measure
    # a burst against
    retries = tm.counters.get("wire.retry")
    if retries is not None or "wire_rtt_p50" in out:
        out["wire_retries"] = float(retries or 0.0)
    for k, v in extra.items():
        if v is not None and k in METRIC_FIELDS:
            out[k] = float(v)
    return out


def emit_alert(tm, alert: dict) -> None:
    """One :data:`ALERT_EVENT` into the telemetry stream — the ONE
    emission point, so the event schema (rule / series / rank / value /
    threshold) cannot drift between collector venues.  Callers guard on
    ``tm.enabled`` (§11; the hot-path checker knows this symbol)."""
    tm.event(ALERT_EVENT, rule=alert.get("rule"),
             series=alert.get("series"), scope=alert.get("scope"),
             worker=alert.get("rank"), value=alert.get("value"),
             threshold=alert.get("threshold"),
             action=alert.get("action"))


class MetricStreamer(threading.Thread):
    """Stream this process's metric snapshots to the collector.

    A daemon thread owning one :class:`~..parallel.wire.WireClient`:
    every ``interval_s`` it builds :func:`snapshot_from_telemetry` (plus
    caller ``extra()`` fields) and sends one :data:`METRICS_OP` request.
    A collector outage is survivable by construction — the wire client
    retries briefly, a failed send is dropped (``fleetmon.send_fail``)
    and the NEXT interval tries again; the snapshot stream needs no
    history, the newest sample is the state."""

    def __init__(self, addr: str, rank: int, role: str = "worker",
                 interval_s: float = 1.0, telemetry_=None,
                 extra: Optional[Callable[[], dict]] = None,
                 clock=None, client=None):
        super().__init__(daemon=True, name=f"fleetmon-stream-{role}{rank}")
        self.addr = str(addr)
        self.rank = int(rank)
        self.role = str(role)
        self.interval_s = float(interval_s)
        self.telemetry = telemetry_
        self.extra = extra
        self.clock = clock or WALL
        if client is None:
            try:
                from ..parallel.wire import WireClient
            except ImportError:
                from theanompi_tpu.parallel.wire import WireClient
            # short budget: a snapshot is disposable — never stall the
            # streamer past its own cadence waiting on a dead collector
            client = WireClient(addr, client_id=f"{self.role}{self.rank}",
                                op_timeout_s=3.0, connect_timeout_s=2.0,
                                max_retries=1, deadline_s=4.0,
                                telemetry_=telemetry.DISABLED)
        self.client = client
        # push() runs on this thread AND from the caller (tests, the
        # final `left` sample in stop()) — the counters need the lock
        self._stats_lock = threading.Lock()
        self.sent = 0
        self.failed = 0
        self._halt = threading.Event()

    def _tm(self):
        return self.telemetry if self.telemetry is not None \
            else telemetry.active()

    def push(self, status: str = "live") -> bool:
        """Build + send one snapshot now; True when it landed."""
        tm = self._tm()
        sample = snapshot_from_telemetry(tm)
        if self.extra is not None:
            try:
                sample.update({k: float(v)
                               for k, v in (self.extra() or {}).items()
                               if v is not None and k in METRIC_FIELDS})
            except Exception:
                pass           # a metrics probe must never kill training
        header = {"op": METRICS_OP, "rank": self.rank, "role": self.role,
                  "status": status}
        try:
            self.client.request(header, json.dumps(sample).encode())
        except (ConnectionError, RuntimeError):
            with self._stats_lock:
                self.failed += 1
            if tm.enabled:
                tm.counter("fleetmon.send_fail")
            return False
        with self._stats_lock:
            self.sent += 1
        if tm.enabled:
            tm.counter("fleetmon.sent")
        return True

    def run(self) -> None:
        while not self._halt.wait(self.interval_s):
            self.push()

    def stop(self, final: bool = True, join_timeout: float = 5.0) -> None:
        """Stop streaming; ``final=True`` sends one last ``left`` sample
        so the collector retires this rank instead of raising a
        heartbeat alert over a clean exit."""
        self._halt.set()
        if self.is_alive():
            self.join(timeout=join_timeout)
        if final:
            self.push(status="left")
        try:
            self.client.close()
        except OSError:
            pass


# -- the collector ------------------------------------------------------------

class SeriesRing:
    """One bounded time series: ``(ts, value)`` samples, newest last."""

    __slots__ = ("samples",)

    def __init__(self, depth: int = 512):
        self.samples: deque = deque(maxlen=int(depth))

    def append(self, ts: float, value: float) -> None:
        self.samples.append((float(ts), float(value)))

    def latest(self) -> Optional[Tuple[float, float]]:
        return self.samples[-1] if self.samples else None

    def window(self, since: float) -> List[Tuple[float, float]]:
        return [(t, v) for t, v in self.samples if t >= since]

    def at_or_before(self, ts: float) -> Optional[Tuple[float, float]]:
        out = None
        for t, v in self.samples:
            if t <= ts:
                out = (t, v)
            else:
                break
        return out


def _quantile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


class FleetCollector:
    """Windowed fleet time series + the SLO rule engine.

    Transport-agnostic: :meth:`ingest` is called by the wire server
    (:class:`FleetMonServer`), by the supervisor for its own liveness,
    and by simfleet's health plane — same method, same semantics.
    ``evaluate()`` runs every rule against the current state; each
    breach EPISODE fires exactly one alert (telemetry event + alert log
    + ``on_alert`` callback + the action queue the supervisor drains).
    Thread-safe; every decision-time comparison goes through the
    injectable clock so simfleet rehearses the engine in virtual time."""

    def __init__(self, rules: Optional[Sequence[dict]] = None,
                 ring_depth: int = 512, eval_window_s: float = 2.0,
                 telemetry_=None, clock=None,
                 on_alert: Optional[Callable[[dict], None]] = None):
        self.rules = validate_rules(DEFAULT_RULES if rules is None
                                    else rules)
        self.ring_depth = int(ring_depth)
        self.eval_window_s = float(eval_window_s)
        self.telemetry = telemetry_
        self.clock = clock or WALL
        self.on_alert = on_alert
        self._lock = threading.Lock()
        # rank -> series name -> SeriesRing
        self.series: Dict[int, Dict[str, SeriesRing]] = {}
        self.roles: Dict[int, str] = {}
        self.last_seen: Dict[int, float] = {}
        self.retired: set = set()          # clean departures: no alerts
        self.samples_ingested = 0
        self.alerts: List[dict] = []       # every alert ever fired
        self.actions: deque = deque()      # alerts with an action, FIFO
        # (rule, scope key) -> {"breach_since": ts|None, "firing": bool}
        self._state: Dict[Tuple[str, Any], dict] = {}
        self.evaluations = 0

    def _tm(self):
        return self.telemetry if self.telemetry is not None \
            else telemetry.active()

    # -- ingest -------------------------------------------------------------

    def ingest(self, sample: Dict[str, Any], rank: int,
               role: str = "worker", status: str = "live",
               now: Optional[float] = None) -> None:
        now = self.clock.now() if now is None else float(now)
        rank = int(rank)
        with self._lock:
            self.samples_ingested += 1
            self.roles[rank] = str(role)
            self.last_seen[rank] = now
            if status == "left":
                self.retired.add(rank)
                return
            self.retired.discard(rank)     # a respawn streams again
            rings = self.series.setdefault(rank, {})
            for name in METRIC_FIELDS:
                v = sample.get(name)
                if v is None:
                    continue
                ring = rings.get(name)
                if ring is None:
                    ring = rings[name] = SeriesRing(self.ring_depth)
                ring.append(now, float(v))

    # -- series views -------------------------------------------------------

    def _ranks_for(self, rule: dict) -> List[int]:
        roles = rule.get("roles")
        return sorted(r for r in self.roles
                      if r not in self.retired
                      and (roles is None or self.roles[r] in roles))

    def _value(self, rule: dict, rank: int, now: float) -> Optional[float]:
        """The rule's series value for one rank at ``now`` — streamed
        latest sample, or the derived heartbeat age."""
        if rule["series"] == "heartbeat_age_s":
            seen = self.last_seen.get(rank)
            return None if seen is None else max(0.0, now - seen)
        ring = self.series.get(rank, {}).get(rule["series"])
        if ring is None:
            return None
        latest = ring.latest()
        return None if latest is None else latest[1]

    def fleet_rollup(self, series: str,
                     quantiles: Sequence[float] = (0.5, 0.9, 1.0),
                     now: Optional[float] = None) -> Dict[str, float]:
        """Percentiles of the latest per-rank values of one series."""
        now = self.clock.now() if now is None else float(now)
        rule = {"series": series}
        with self._lock:
            ranks = [r for r in self.roles if r not in self.retired]
            vals = [v for v in (self._value(rule, r, now) for r in ranks)
                    if v is not None]
        out = {}
        for q in quantiles:
            v = _quantile(vals, q)
            if v is not None:
                out[f"p{int(q * 100)}"] = round(v, 6)
        out["n"] = len(vals)
        return out

    # -- the rule engine ----------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass: returns the alerts that fired NOW.

        Episode semantics (the no-flapping contract): per (rule, scope
        key) the engine tracks when the breach began; ``sustained``
        fires once the breach has held ``window_s``, ``threshold`` /
        ``rate_of_change`` / ``fleet_quantile`` fire on the first
        breaching evaluation — and NONE re-fire until an evaluation has
        seen the condition false (which also resets the sustain
        window)."""
        now = self.clock.now() if now is None else float(now)
        fired: List[dict] = []
        with self._lock:
            for rule in self.rules:
                scope = rule.get("scope", "fleet" if rule["predicate"] ==
                                 "fleet_quantile" else "rank")
                if scope == "fleet" or rule["predicate"] == "fleet_quantile":
                    keys = [(None, self._fleet_value(rule, now))]
                else:
                    keys = [(r, self._rank_value(rule, r, now))
                            for r in self._ranks_for(rule)]
                for rank, value in keys:
                    if value is None:
                        continue
                    st = self._state.setdefault(
                        (rule["name"], rank),
                        {"breach_since": None, "firing": False})
                    breach = _cmp(rule.get("op", ">"), value,
                                  float(rule["value"]))
                    if not breach:
                        st["breach_since"] = None
                        st["firing"] = False
                        continue
                    if st["breach_since"] is None:
                        st["breach_since"] = now
                    need = float(rule.get("window_s", 0.0)) \
                        if rule["predicate"] == "sustained" else 0.0
                    if st["firing"] or now - st["breach_since"] < need:
                        continue
                    st["firing"] = True
                    alert = {"ts": round(now, 3), "rule": rule["name"],
                             "series": rule["series"],
                             "predicate": rule["predicate"],
                             "scope": "fleet" if rank is None else "rank",
                             "rank": rank, "value": round(value, 6),
                             "threshold": float(rule["value"]),
                             "action": rule.get("action")}
                    fired.append(alert)
            self.evaluations += 1
            self.alerts.extend(fired)
            for a in fired:
                if a["action"]:
                    self.actions.append(a)
        tm = self._tm()
        for a in fired:
            if tm.enabled:
                emit_alert(tm, a)
            if self.on_alert is not None:
                self.on_alert(a)
        return fired

    def _rank_value(self, rule: dict, rank: int,
                    now: float) -> Optional[float]:
        if rule["predicate"] == "rate_of_change":
            ring = self.series.get(rank, {}).get(rule["series"])
            if ring is None:
                return None
            latest = ring.latest()
            base = ring.at_or_before(now - float(rule["window_s"]))
            if latest is None or base is None or latest[0] <= base[0]:
                return None
            return (latest[1] - base[1]) / (latest[0] - base[0])
        return self._value(rule, rank, now)

    def _fleet_value(self, rule: dict, now: float) -> Optional[float]:
        vals = [v for v in (self._value(rule, r, now)
                            for r in self._ranks_for(rule))
                if v is not None]
        if len(vals) < 2:
            return None        # one rank is not a fleet — no quantile
        return _quantile(vals, float(rule.get("quantile", 0.5)))

    def pop_actions(self) -> List[dict]:
        """Drain the action queue (the supervisor's per-tick read)."""
        with self._lock:
            out = list(self.actions)
            self.actions.clear()
        return out

    # -- exposition ---------------------------------------------------------

    def expose_text(self, now: Optional[float] = None) -> str:
        """Prometheus-style text exposition: one
        ``theanompi_<series>{rank=...,role=...}`` line per live rank per
        registered series, fleet rollup gauges, and the alert counter —
        every name in :data:`FLEET_SERIES` appears even when no rank
        streams it yet (schema guarantee: scraping never misses a series
        because the fleet is young)."""
        now = self.clock.now() if now is None else float(now)
        lines: List[str] = []
        with self._lock:
            ranks = sorted(r for r in self.roles if r not in self.retired)
            for name in FLEET_SERIES:
                metric = "theanompi_" + name
                lines.append(f"# TYPE {metric} gauge")
                for rank in ranks:
                    v = self._value({"series": name}, rank, now)
                    if v is None:
                        continue
                    lines.append(
                        f'{metric}{{rank="{rank}",'
                        f'role="{self.roles[rank]}"}} {v:g}')
            lines.append("# TYPE theanompi_fleet_alerts_total counter")
            lines.append(f"theanompi_fleet_alerts_total {len(self.alerts)}")
            lines.append("# TYPE theanompi_fleet_ranks gauge")
            lines.append(f"theanompi_fleet_ranks {len(ranks)}")
        return "\n".join(lines) + "\n"

    def status(self) -> dict:
        with self._lock:
            return {"ranks": sorted(self.roles),
                    "retired": sorted(self.retired),
                    "samples": self.samples_ingested,
                    "evaluations": self.evaluations,
                    "alerts": len(self.alerts),
                    "rules": [r["name"] for r in self.rules]}

    # -- crash-recovery snapshots (the §14 discipline) ----------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "series": {str(r): {n: list(ring.samples)
                                    for n, ring in rings.items()}
                           for r, rings in self.series.items()},
                "roles": {str(r): v for r, v in self.roles.items()},
                "last_seen": {str(r): v for r, v in self.last_seen.items()},
                "retired": sorted(self.retired),
                "alerts": list(self.alerts),
                "state": [[name, rank, dict(st)] for (name, rank), st
                          in self._state.items()],
                "samples": self.samples_ingested,
            }

    def restore(self, snap: dict) -> None:
        with self._lock:
            self.series = {}
            for r, rings in (snap.get("series") or {}).items():
                dst = self.series[int(r)] = {}
                for n, samples in rings.items():
                    ring = dst[n] = SeriesRing(self.ring_depth)
                    for ts, v in samples:
                        ring.append(ts, v)
            self.roles = {int(r): str(v)
                          for r, v in (snap.get("roles") or {}).items()}
            self.last_seen = {int(r): float(v) for r, v in
                              (snap.get("last_seen") or {}).items()}
            self.retired = set(int(r) for r in snap.get("retired", ()))
            self.alerts = list(snap.get("alerts") or ())
            self._state = {(str(name), rank): dict(st)
                           for name, rank, st in snap.get("state", ())}
            self.samples_ingested = int(snap.get("samples", 0))


# -- alert → supervision ------------------------------------------------------

def apply_alert(controller, alert: dict) -> bool:
    """Feed one actionable alert into the membership plane: a per-rank
    ``demote`` alert drives the EXISTING demotion path with the firing
    rule cited in the ``worker_demote`` event (``rule=`` — the §20
    closed loop; the schema-drift checker pins that cited names exist in
    the rule set).  Returns True when a demotion actually happened (the
    controller still owns the min-active floor)."""
    if alert.get("action") != "demote" or alert.get("rank") is None:
        return False
    return controller.demote(
        int(alert["rank"]), reason="alert", rule=alert.get("rule"),
        series=alert.get("series"), value=alert.get("value"))


def fleet_flight_dump(record_dir: str, reason: str,
                      timeout_s: float = 2.0) -> List[str]:
    """Ask every registered statusz endpoint to dump its flight ring
    (the §17 ``flight`` op) — the fleet-wide what-was-everyone-doing
    trail a fleet-scoped alert (``queue_starved``) triggers.  Returns
    the dump paths the endpoints reported."""
    try:
        from . import tracing
    except ImportError:
        from theanompi_tpu.utils import tracing
    paths: List[str] = []
    for doc in tracing.read_statusz_docs(record_dir):
        addr = f"{doc.get('host', '127.0.0.1')}:{doc.get('port')}"
        try:
            rep = tracing.statusz_query(addr, "flight", timeout_s=timeout_s)
        except Exception:
            continue               # a DOWN process dumped on its own way out
        if rep.get("path"):
            paths.append(rep["path"])
    return paths


# -- the live chaos alert-audit -----------------------------------------------

def alert_deadline_s(rule: dict, duration_s: float, eval_window_s: float,
                     interval_s: float) -> float:
    """How long after a fault LANDS its alert may legitimately take:
    the fault's own duration (a window's symptom may persist until it
    closes), the rule's detection budget (a heartbeat threshold IS
    seconds-of-silence before the symptom exists; a sustained window
    must fill), one streamer interval (the sample that carries the
    symptom), and ONE evaluation window — the §20 acceptance bound."""
    budget = float(duration_s) + float(interval_s) + float(eval_window_s)
    budget += float(rule.get("window_s", 0.0) or 0.0)
    if rule.get("series") == "heartbeat_age_s":
        budget += float(rule.get("value", 0.0))
    return budget


def audit_alerts(alert_events: Sequence[dict], realized: Sequence[dict],
                 rules: Sequence[dict], eval_window_s: float,
                 interval_s: float = 1.0) -> Tuple[bool, List[str]]:
    """The chaos harness's closing check: every LANDED fault whose
    symptom a rule covers must have produced its alert within one
    evaluation window of the symptom becoming visible
    (:func:`alert_deadline_s`).

    ``alert_events`` are :data:`ALERT_EVENT` telemetry events (or the
    collector's own alert log — same schema), ``realized`` the realized-
    schedule docs (``chaos_realized.jsonl`` lines / simfleet export) in
    the SAME time base as the alerts (wall epoch live, virtual seconds
    in a rehearsal).  A fault is COVERED when a rule named by
    :data:`FAULT_ALERT_COVERAGE` for its kind is in the active rule set.
    Returns ``(ok, lines)`` — lines name every fault checked and every
    miss."""
    by_name = {r["name"]: r for r in rules}
    lines: List[str] = []
    ok = True
    alerts = [dict(a) for a in alert_events]
    for a in alerts:
        # telemetry events carry the alerted rank as `worker`
        # (emit_alert) — their envelope `rank` is the EMITTING process
        # (the collector's registry), which must not shadow the target;
        # collector-log alerts carry `rank` and no `worker`
        if "worker" in a:
            a["rank"] = a.get("worker")
    for doc in realized:
        if doc.get("error"):
            continue                       # never landed — no symptom owed
        kind = str(doc.get("kind"))
        covered = [n for n in FAULT_ALERT_COVERAGE.get(kind, ())
                   if n in by_name]
        if not covered:
            continue
        target = doc.get("target")
        t_fault = float(doc.get("ts", doc.get("rel", 0.0)))
        deadline = t_fault + max(
            alert_deadline_s(by_name[n], doc.get("duration", 0.0),
                             eval_window_s, interval_s) for n in covered)
        hit = None
        for a in alerts:
            if a.get("rule") not in covered:
                continue
            if a.get("rank") is not None and target not in (-1, None) \
                    and int(a["rank"]) != int(target):
                continue
            ats = float(a.get("ts", 0.0))
            if t_fault <= ats <= deadline:
                hit = a
                break
        if hit is None:
            ok = False
            lines.append(
                f"ALERT-AUDIT FAIL: {kind}@{round(t_fault, 1)} on "
                f"w{target} raised none of {covered} by "
                f"+{round(deadline - t_fault, 1)}s")
        else:
            lines.append(
                f"alert-audit: {kind} on w{target} -> {hit['rule']} "
                f"(+{round(float(hit['ts']) - t_fault, 1)}s, value "
                f"{hit.get('value')})")
    return ok, lines


# -- the collector service ----------------------------------------------------

class FleetMonServer:
    """Serve a :class:`FleetCollector` over the §15 wire framing.

    Ops: :data:`METRICS_OP` (ingest one snapshot — dedup-windowed, so a
    wire-retried sample lands once), ``series`` (one rank+series window),
    ``rollup`` (fleet percentiles), ``alerts`` (the alert log tail),
    ``exposition`` (the Prometheus-style text, as the reply body), and
    ``health`` (statusz-compatible: fleetz probes this server like any
    other roster entry).  A discovery doc registers under
    ``<run_dir>/statusz/`` with role ``fleetmon``; an evaluation thread
    runs the rule engine every ``eval_window_s``; ``snapshot_dir``
    enables §14 crash-atomic state snapshots restored on start."""

    def __init__(self, collector: Optional[FleetCollector] = None,
                 rules: Optional[Sequence[dict]] = None,
                 run_dir: Optional[str] = None,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every_s: float = 2.0,
                 eval_window_s: float = 2.0,
                 idle_timeout_s: float = 60.0, telemetry_=None):
        self.collector = collector if collector is not None else \
            FleetCollector(rules=rules, eval_window_s=eval_window_s,
                           telemetry_=telemetry_)
        self.run_dir = run_dir
        self.snapshot_dir = snapshot_dir
        self.snapshot_every_s = float(snapshot_every_s)
        self.idle_timeout_s = float(idle_timeout_s)
        self.telemetry = telemetry_
        self.t0 = time.time()
        self._srv = None
        self._thread: Optional[threading.Thread] = None
        self._eval_thread: Optional[threading.Thread] = None
        self._halt = threading.Event()
        self._doc_path: Optional[str] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        try:
            from ..parallel import wire as _wire
        except ImportError:
            from theanompi_tpu.parallel import wire as _wire
        self._wire = _wire
        self.dedup = _wire.DedupWindow(depth=256,
                                       telemetry_=telemetry.DISABLED)

    def _tm(self):
        return self.telemetry if self.telemetry is not None \
            else telemetry.active()

    # -- snapshots ----------------------------------------------------------

    def _snap_path(self) -> Optional[str]:
        return os.path.join(self.snapshot_dir, "fleetmon_state.json") \
            if self.snapshot_dir else None

    def snapshot(self) -> Optional[str]:
        path = self._snap_path()
        if not path:
            return None
        try:
            from .checkpoint import _fsync_write
        except ImportError:
            from theanompi_tpu.utils.checkpoint import _fsync_write
        os.makedirs(self.snapshot_dir, exist_ok=True)
        state = {"collector": self.collector.snapshot(),
                 "dedup": self.dedup.snapshot()}
        _fsync_write(path, lambda f: f.write(
            json.dumps(state, sort_keys=True).encode()))
        return path

    def restore(self) -> bool:
        path = self._snap_path()
        if not path or not os.path.exists(path):
            return False
        try:
            with open(path) as f:
                state = json.load(f)
            self.collector.restore(state.get("collector") or {})
            self.dedup.restore(state.get("dedup") or {})
        except (ValueError, OSError):
            return False           # torn/garbage snapshot: start fresh
        return True

    def _eval_loop(self) -> None:
        last_mark = None
        while not self._halt.wait(self.collector.eval_window_s):
            try:
                self.collector.evaluate()
                if self.snapshot_dir:
                    c = self.collector
                    mark = (c.samples_ingested, len(c.alerts))
                    if mark != last_mark:
                        self.snapshot()
                        last_mark = mark
            except Exception:
                pass               # evaluation must never kill serving

    # -- serving ------------------------------------------------------------

    def status(self) -> dict:
        tm = self._tm()
        out = {"ok": True, "role": "fleetmon", "id": 0,
               "pid": os.getpid(),
               "uptime_s": round(time.time() - self.t0, 1),
               "run": getattr(tm, "run_id", None)}
        out.update(self.collector.status())
        return out

    def start(self, host: str = "127.0.0.1",
              port: int = 0) -> Tuple[str, int]:
        import socketserver
        wire = self._wire
        collector = self.collector
        dedup = self.dedup
        idle = self.idle_timeout_s
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                self.request.settimeout(idle)
                with outer._conns_lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        try:
                            header, body = wire.recv_msg(self.request)
                        except wire.VersionMismatch as e:
                            wire.send_msg(self.request,
                                          {"ok": False, "error": str(e)})
                            return
                        except wire.CorruptPayload as e:
                            wire.send_msg(self.request,
                                          {"ok": False, "error": str(e),
                                           "retry": True})
                            continue
                        try:
                            self._dispatch(header, body)
                        except (ConnectionError, OSError):
                            raise
                        except Exception as e:
                            wire.send_msg(self.request,
                                          {"ok": False, "error": repr(e)})
                except Exception:
                    return         # peer gone / idle / bad frame: drop it
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

            def _dispatch(self, header, body):
                op = header.get("op")
                tok = header.get("tok")
                if op == METRICS_OP:
                    dup, cached = dedup.check(tok, op)
                    if dup:
                        # a retried snapshot (reply lost in flight): the
                        # original landed — ack without re-ingesting
                        wire.send_msg(self.request,
                                      {"ok": True, "dedup": True})
                        return
                    try:
                        sample = json.loads(body.decode()) if body else {}
                        collector.ingest(
                            sample, rank=int(header.get("rank", 0)),
                            role=str(header.get("role", "worker")),
                            status=str(header.get("status", "live")))
                        dedup.record(tok, op, {"ok": True})
                    except Exception:
                        dedup.release(tok, op)
                        raise
                    wire.send_msg(self.request, {"ok": True})
                elif op == "series":
                    rank = int(header.get("rank", 0))
                    name = str(header.get("series"))
                    # under the collector lock: ingest appends to the
                    # ring concurrently, and copying a mutating deque
                    # raises mid-iteration
                    with collector._lock:
                        ring = collector.series.get(rank, {}).get(name)
                        samples = list(ring.samples) if ring else []
                    wire.send_msg(self.request,
                                  {"ok": True, "samples": samples})
                elif op == "rollup":
                    wire.send_msg(self.request, {
                        "ok": True,
                        "rollup": collector.fleet_rollup(
                            str(header.get("series")))})
                elif op == "alerts":
                    n = int(header.get("n", 32))
                    with collector._lock:
                        tail = collector.alerts[-n:]
                    wire.send_msg(self.request,
                                  {"ok": True, "alerts": tail})
                elif op == "exposition":
                    wire.send_msg(self.request, {"ok": True},
                                  collector.expose_text().encode())
                elif op in ("health", "events"):
                    # statusz-compatible: fleetz probes this roster entry
                    # with the same ops it sends every other process
                    if op == "health":
                        wire.send_msg(self.request, outer.status())
                    else:
                        tm = outer._tm()
                        evs = tm.tail(int(header.get("n", 16))) \
                            if tm.enabled else []
                        wire.send_msg(self.request,
                                      {"ok": True, "events": evs})
                else:
                    wire.send_msg(self.request,
                                  {"ok": False,
                                   "error": f"unknown fleetmon op {op!r}"})

        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._srv = socketserver.ThreadingTCPServer((host, port), Handler)
        self._srv.daemon_threads = True
        self.restore()
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True, name="fleetmon-serve")
        self._thread.start()
        self._eval_thread = threading.Thread(target=self._eval_loop,
                                             daemon=True,
                                             name="fleetmon-eval")
        self._eval_thread.start()
        host, port = self._srv.server_address[:2]
        if self.run_dir:
            try:
                from . import tracing
            except ImportError:
                from theanompi_tpu.utils import tracing
            d = tracing.statusz_dir(self.run_dir)
            try:
                os.makedirs(d, exist_ok=True)
                self._doc_path = os.path.join(d, "fleetmon_0.json")
                tmp = f"{self._doc_path}.tmp{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump({"role": "fleetmon", "id": 0,
                               "pid": os.getpid(), "host": host,
                               "port": port, "ts": time.time()}, f)
                os.replace(tmp, self._doc_path)
            except OSError:
                self._doc_path = None     # discovery is best-effort
        return host, port

    def stop(self, deregister: bool = True,
             final_snapshot: bool = True) -> None:
        self._halt.set()
        if self._eval_thread is not None:
            self._eval_thread.join(timeout=10)
            self._eval_thread = None
        if final_snapshot and self.snapshot_dir:
            try:
                self.snapshot()
            except Exception:
                pass
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
            # a collector death severs every in-flight connection; an
            # in-process stop must too, or persistent streamer
            # connections keep feeding a 'dead' collector (and restart
            # tests test nothing)
            with self._conns_lock:
                conns = list(self._conns)
                self._conns.clear()
            for c in conns:
                try:
                    c.close()
                except OSError:
                    pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._doc_path is not None:
            if deregister:
                try:
                    os.remove(self._doc_path)
                except OSError:
                    pass
            self._doc_path = None
