"""Persistent AOT executable cache: compile once, deserialize forever.

Theano-MPI paid one Theano compile per worker at session start and amortized
it over the whole run; this rebuild pays the equivalent XLA compile on EVERY
process start — and round-5 forensics (WEDGE.md) measured 26–270 s per
program over the tunnel, with a mid-pass wedge discarding the warm
executables along with the process.  The XLA *compilation* cache
(``jax_compilation_cache_dir``) was supposed to absorb this, but its key is
opaque to us and the round-5 experiment showed the topology-AOT venue's
read path simply not hitting.  This module sidesteps the question by
serializing the compiled executables OURSELVES
(``jax.experimental.serialize_executable``) under a key WE control.

**The key** (content-addressed, sha256 over a canonical JSON):

* the StableHLO hash of the lowered program — shapes, dtypes, shardings,
  the whole traced computation;
* ``jax.__version__`` / ``jaxlib.__version__`` (an executable must never
  be loaded into a different runtime than compiled it);
* platform + device kind of the target mesh (``tpu``/``cpu``,
  ``TPU v5 lite``/...) — deliberately NOT ``platform_version``: that was
  the opaque variable suspected of breaking the round-5 XLA-cache
  experiment, and PJRT executables are compatible across patch builds of
  the same device kind (a genuinely incompatible blob still fails loudly
  at deserialize and falls back to a fresh compile);
* mesh axis names + shape;
* the donation signature (which flat args are donated);
* the PRNG impl (``rbg`` vs ``threefry2x32`` change the key dtype AND the
  lowered program, but belt-and-braces);
* caller extras (fn name, rule signature, steps_per_call, ...).

**The fallback ladder** (``get_or_compile``): hit (deserialize, ~ms) →
deserialize-fallback (corrupt blob / version drift → fresh compile,
counter incremented, entry rewritten) → fresh compile + serialize →
serialize-unsupported (backend can't export → fresh compile result is
still returned; only persistence is lost).  The cache can never make a
run fail: every cache-side error degrades to the plain compile.

Entry format, one file per key (``<key>.jexec``): a one-line JSON header
(versions, label, platform — checked BEFORE unpickling) followed by the
pickled ``(payload, in_tree, out_tree)`` triple from
``serialize_executable.serialize``.  A ``manifest.json`` sidecar holds
human-readable metadata per key for ``scripts/prewarm_cache.py`` and
post-mortems.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import sys
import time
from typing import Any, Dict, Optional, Tuple

ENV_CACHE_DIR = "THEANOMPI_COMPILE_CACHE"

_FORMAT = 1
_MAGIC = "theanompi-aot"

# one shared instance per directory, so hit counters aggregate across every
# compile surface of the process (model, bench, prewarm)
_INSTANCES: Dict[str, "CompileCache"] = {}


class _EntryMismatch(Exception):
    """Header/runtime disagreement (version drift, truncation) — triggers
    the deserialize-fallback rung, never an error."""


def _versions() -> Tuple[str, str]:
    import jax
    import jaxlib
    return jax.__version__, jaxlib.__version__


def _mesh_device(mesh):
    """First device of the target mesh — works for runtime meshes AND
    topology-AOT meshes (non-addressable devices still report platform and
    device_kind, which is all the key reads)."""
    if mesh is None:
        import jax
        return jax.devices()[0]
    return next(iter(mesh.devices.flat))


def _donation_signature(lowered) -> Tuple:
    """Which flat args are donated, from the Lowered's args_info (best
    effort — absent attributes degrade to an empty signature rather than
    blocking the cache)."""
    try:
        import jax
        return tuple(bool(getattr(a, "donated", False))
                     for a in jax.tree_util.tree_leaves(lowered.args_info))
    except Exception:
        return ()


def program_key(lowered, mesh=None, extra: Optional[dict] = None) -> str:
    """Content-addressed key for one lowered program on one target."""
    import jax
    dev = _mesh_device(mesh)
    jax_v, jaxlib_v = _versions()
    parts = {
        "stablehlo": hashlib.sha256(
            lowered.as_text().encode("utf-8")).hexdigest(),
        "jax": jax_v,
        "jaxlib": jaxlib_v,
        "platform": getattr(dev, "platform", "?"),
        "device_kind": getattr(dev, "device_kind", "?"),
        "mesh": None if mesh is None else
        {"axes": list(mesh.axis_names),
         "shape": [int(mesh.shape[a]) for a in mesh.axis_names]},
        "donate": list(_donation_signature(lowered)),
        "prng": str(jax.config.jax_default_prng_impl),
        "extra": extra or {},
    }
    blob = json.dumps(parts, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:40]


def donated_load_safe(mesh=None) -> bool:
    """Whether this backend is trusted to EXECUTE deserialized executables
    whose inputs are donated (input-output aliased).

    On the CPU backend of this jaxlib (0.4.36), repeatedly executing a
    DESERIALIZED donated SPMD executable corrupts the heap (glibc
    "corrupted double-linked list" after 2–3 calls; reproduced with a raw
    8-device shard_map momentum step, aliasing metadata and donated flags
    intact across the round-trip — the same fragile serialization layer
    whose cache-write path segfaulted test_3d_mesh in round 6,
    tests/conftest.py NOTE).  Donation-FREE deserialized executables are
    stable (50-call soak).  So on non-TPU platforms the AOT cache compiles
    and loads donation-free variants of the donated programs — identical
    math, transiently higher memory, and a distinct cache key (the
    donation signature is part of the key, so the two variants can share
    a directory).  ``THEANOMPI_AOT_DONATE=1|0`` overrides the platform
    default (e.g. to re-test a fixed jaxlib)."""
    env = os.environ.get("THEANOMPI_AOT_DONATE")
    if env is not None:
        return env == "1"
    return getattr(_mesh_device(mesh), "platform", "") == "tpu"


def program_summary(compiled) -> dict:
    """Best-effort cost/memory summary of one compiled executable for the
    manifest (``scripts/explain_program.py`` reads it): XLA
    ``cost_analysis`` (flops, bytes accessed) + ``memory_analysis``
    (argument/output/temp/code bytes, and their sum as the HBM-peak
    estimate).  A cache hit skips the recompute — the summary was taken at
    write time, when the fresh executable was in hand.  Every probe is
    fenced: a backend that reports nothing (or nonsense like -1) yields a
    smaller dict, never an error."""
    out: Dict[str, float] = {}
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        if isinstance(ca, dict):
            for src, dst in (("flops", "flops"),
                             ("bytes accessed", "bytes_accessed"),
                             ("transcendentals", "transcendentals")):
                v = ca.get(src)
                if v is not None and float(v) > 0:
                    out[dst] = float(v)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for attr, dst in (("argument_size_in_bytes", "argument_bytes"),
                          ("output_size_in_bytes", "output_bytes"),
                          ("temp_size_in_bytes", "temp_bytes"),
                          ("alias_size_in_bytes", "alias_bytes"),
                          ("generated_code_size_in_bytes",
                           "generated_code_bytes")):
            v = getattr(ma, attr, None)
            if v is not None and int(v) >= 0:
                out[dst] = int(v)
        if {"argument_bytes", "output_bytes", "temp_bytes"} <= out.keys():
            # aliased (donated) buffers are counted inside argument_bytes
            # and reused for output — subtract so donation shows up as the
            # memory win it is
            out["peak_hbm_bytes_est"] = (
                out["argument_bytes"] + out["output_bytes"]
                + out["temp_bytes"] - out.get("alias_bytes", 0))
    except Exception:
        pass
    return out


def key_extra(fn: str, model=None, exchanger=None,
              spc: Optional[int] = None) -> dict:
    """The caller-extras dict EVERY compile surface must build the same way
    (model_base, bench.py, scripts/prewarm_cache.py) — a drifted extras
    dict silently forfeits the prewarm hit, so the composition lives here.

    The rule signature is belt-and-braces over the HLO hash: two rules
    that happened to lower identically must still never share an entry.
    ``spc`` is stamped only when the caller passes it (the train surface):
    spc-independent programs (val, the standalone exchange, zero-shadow,
    fsdp-val) are byte-identical across spc variants of a row, and keying
    them per-spc would compile and store one redundant twin per variant.
    """
    extra: Dict[str, Any] = {"fn": str(fn)}
    if model is not None:
        extra["model"] = type(model).__name__
        extra["n_subb"] = int(getattr(model, "n_subb", 1))
        v = int(getattr(model, "pp_interleave", 1) or 1)
        if v > 1:
            # the interleaved pipeline schedule reshapes the whole scan
            # (chunked layers, ring hops, v·M+pp−1 ticks) — interleaved and
            # fill/drain builds of the same row must never share an entry.
            # Stamped only when v > 1 so every pre-existing key (and every
            # prewarmed fill/drain entry) stays byte-stable.
            extra["pp_interleave"] = v
        cfg = getattr(model, "config", {}) or {}
        if str(fn) == "train" and cfg.get("numerics", False) \
                and getattr(model, "_fsdp", None) is None:
            # the numerics health plane adds the aux out-path + cadence
            # cond to the traced TRAIN step only (utils/numerics) —
            # stamped only when effectively ON (fsdp builds stay inert),
            # so every pre-existing key (and every numerics-off build)
            # stays byte-stable
            from . import numerics as _numerics
            extra["numerics"] = _numerics.cadence(cfg)
        if getattr(model, "config", {}).get("update_sharding", False):
            # leaf-wise update-plane sharding reshapes the step (chunked
            # moments, fused allgather) AND its state avals; the threshold
            # moves leaves between the sharded/replicated layouts, so it
            # is part of the identity.  Stamped only when the knob is on —
            # every pre-existing key (zero_opt sessions included) stays
            # byte-stable.
            mb = model.config.get("ushard_min_bytes")
            if mb is None:
                # update_sharding imports jax at module scope — resolve
                # its default only when the config doesn't pin one, so
                # jax-free callers (the schema-drift key_extra probe)
                # can build extras without a backend
                from ..parallel import update_sharding as _us
                mb = _us.DEFAULT_MIN_BYTES
            extra["ushard"] = int(mb)
    if spc is not None:
        extra["spc"] = int(spc)
    if exchanger is not None:
        strat = getattr(exchanger, "strategy", None)
        extra["rule"] = ":".join(
            str(x) for x in (type(exchanger).__name__,
                             getattr(exchanger, "mode", ""),
                             getattr(strat, "name", ""),
                             getattr(exchanger, "exchange_freq", 1)))
        bb = int(getattr(exchanger, "bucket_bytes", 0) or 0)
        if bb:
            # the bucketed-wire schedule (parallel/buckets.py) reshapes
            # the collective sequence: a bucketed and a monolithic build
            # of the same rule must never share an entry (belt-and-braces
            # over the HLO hash, like the rule signature)
            extra["bucket_bytes"] = bb
    if os.environ.get("THEANOMPI_TPU_NO_PALLAS", "0") == "1":
        # the compression/LRN ops dispatch to the jnp oracles instead of
        # the Pallas kernels (ops/_pallas_util) — a different program with
        # the same config, so the forced-oracle build must never share an
        # entry with the kernel build.  Stamped only when forced, so every
        # pre-existing key (and every default TPU build) stays byte-stable.
        extra["no_pallas"] = 1
    return extra


class CompileCache:
    """One cache directory: content-addressed ``.jexec`` entries + manifest.

    ``enabled=False`` builds the inert no-op instance — ``get_or_compile``
    then just compiles and reports ``cache: 'off'`` (the pre-cache
    behavior, bit for bit).
    """

    def __init__(self, cache_dir: Optional[str], enabled: bool = True):
        self.cache_dir = cache_dir
        self.enabled = bool(enabled and cache_dir)
        self.counters = {"hits": 0, "misses": 0, "deserialize_fallbacks": 0,
                         "serialize_unsupported": 0}
        if self.enabled:
            try:
                os.makedirs(self.cache_dir, exist_ok=True)
            except OSError as e:
                # an uncreatable dir (read-only mount, a file in the way)
                # must degrade to the plain compile, not crash the run —
                # the module contract: every cache-side error is non-fatal
                print(f"compile_cache: cannot create {self.cache_dir} "
                      f"({e}) — cache disabled", file=sys.stderr)
                self.enabled = False

    def _tick(self, kind: str) -> None:
        """Bump a ladder counter, mirrored into the process telemetry
        registry (``compile_cache.<kind>``) so run reports and the flight
        recorder see where executables came from."""
        self.counters[kind] += 1
        from . import telemetry
        tm = telemetry.active()
        if tm.enabled:
            tm.counter("compile_cache." + kind)

    # -- entry IO ----------------------------------------------------------

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, key + ".jexec")

    def has(self, key: str) -> bool:
        return self.enabled and os.path.exists(self._path(key))

    def _write_entry(self, key: str, label: str, payload: bytes,
                     in_tree, out_tree, device) -> None:
        jax_v, jaxlib_v = _versions()
        header = {"magic": _MAGIC, "format": _FORMAT,
                  "jax": jax_v, "jaxlib": jaxlib_v,
                  "platform": getattr(device, "platform", "?"),
                  "device_kind": getattr(device, "device_kind", "?"),
                  "label": label, "created": time.time()}
        tmp = self._path(key) + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(json.dumps(header).encode("utf-8") + b"\n")
            f.write(pickle.dumps((payload, in_tree, out_tree)))
        os.replace(tmp, self._path(key))     # atomic: readers never see half

    def _parse_header(self, head: bytes) -> dict:
        """Validate one entry's header line.  Raises ``_EntryMismatch`` on
        format/version drift or structural damage."""
        try:
            header = json.loads(head.decode("utf-8"))
        except ValueError as e:
            raise _EntryMismatch(f"unparseable header: {e}") from e
        if header.get("magic") != _MAGIC:
            raise _EntryMismatch("bad magic")
        if header.get("format") != _FORMAT:
            raise _EntryMismatch(
                f"entry format {header.get('format')!r}, reader speaks "
                f"{_FORMAT}")
        jax_v, jaxlib_v = _versions()
        if (header.get("jax"), header.get("jaxlib")) != (jax_v, jaxlib_v):
            raise _EntryMismatch(
                f"built on jax {header.get('jax')}/jaxlib "
                f"{header.get('jaxlib')}, runtime is {jax_v}/{jaxlib_v}")
        return header

    def check_header(self, key: str) -> None:
        """Header-only validation (one readline, no unpickle) — the
        ``load=False`` prewarm rung, so a damaged or version-drifted entry
        is recompiled OFF-line instead of surfacing as a
        deserialize-fallback paying the full compile in the hardware
        window."""
        with open(self._path(key), "rb") as f:
            self._parse_header(f.readline())

    def _read_entry(self, key: str):
        """Header-checked read.  Raises ``_EntryMismatch`` on version drift
        or structural damage — the caller's deserialize-fallback rung."""
        with open(self._path(key), "rb") as f:
            header = self._parse_header(f.readline())
            try:
                payload, in_tree, out_tree = pickle.loads(f.read())
            except Exception as e:
                raise _EntryMismatch(f"corrupt body: {e!r}") from e
        return header, payload, in_tree, out_tree

    # -- the ladder --------------------------------------------------------

    def get_or_compile(self, lowered, label: str = "", mesh=None,
                       extra: Optional[dict] = None, load: bool = True):
        """Return ``(compiled, info)`` for one lowered program.

        ``info``: ``cache`` ∈ {hit, miss, deserialize_fallback, off},
        ``compile_secs`` (wall time of whichever path ran — the
        deserialize for a hit, the XLA compile otherwise), ``key``,
        ``serialized`` (did the entry land on disk).

        ``load=False`` (prewarm): a present entry is trusted from its
        header and NOT deserialized — the off-line venue has no runtime
        client to load into; returns ``(None, info)`` on a hit.
        """
        t0 = time.time()
        if not self.enabled:
            compiled = lowered.compile()
            return compiled, {"cache": "off", "key": None, "label": label,
                              "compile_secs": round(time.time() - t0, 3),
                              "serialized": False}
        key = program_key(lowered, mesh=mesh, extra=extra)
        info: Dict[str, Any] = {"cache": "miss", "key": key, "label": label,
                                "serialized": False}
        if self.has(key):
            if not load:
                try:
                    self.check_header(key)
                except Exception as e:
                    # a damaged/drifted entry found OFF-line: recompile it
                    # now, not in the hardware window
                    self._tick("deserialize_fallbacks")
                    info["cache"] = "deserialize_fallback"
                    info["fallback_reason"] = str(e)[:300]
                    print(f"compile_cache: entry {key[:12]} unusable "
                          f"({str(e)[:200]}) — re-prewarming",
                          file=sys.stderr)
                else:
                    self._tick("hits")
                    self._bump_manifest(key, label)
                    info.update(cache="hit",
                                compile_secs=round(time.time() - t0, 3))
                    return None, info
            else:
                try:
                    from jax.experimental import serialize_executable as se
                    _, payload, in_tree, out_tree = self._read_entry(key)
                    backend = getattr(_mesh_device(mesh), "client", None)
                    compiled = se.deserialize_and_load(
                        payload, in_tree, out_tree, backend=backend)
                    self._tick("hits")
                    self._bump_manifest(key, label)
                    info.update(cache="hit",
                                compile_secs=round(time.time() - t0, 3))
                    return compiled, info
                except Exception as e:
                    # corrupt blob, version drift, backend refusal — rung 2:
                    # count it, recompile fresh, rewrite the entry below
                    self._tick("deserialize_fallbacks")
                    info["cache"] = "deserialize_fallback"
                    info["fallback_reason"] = str(e)[:300]
                    print(f"compile_cache: entry {key[:12]} unusable "
                          f"({str(e)[:200]}) — recompiling", file=sys.stderr)
        if info["cache"] == "miss":
            self._tick("misses")
        t0 = time.time()
        compiled = lowered.compile()
        compile_secs = time.time() - t0
        info["compile_secs"] = round(compile_secs, 3)
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
            self._write_entry(key, label, payload, in_tree, out_tree,
                              _mesh_device(mesh))
            self._record_manifest(key, label, compile_secs, len(payload),
                                  mesh, compiled=compiled, extra=extra)
            info["serialized"] = True
        except Exception as e:
            # rung 4: the backend (or this program shape) can't serialize —
            # the fresh compile is still perfectly usable, only persistence
            # is lost.  Harmless by design.
            self._tick("serialize_unsupported")
            info["serialize_error"] = str(e)[:300]
            print(f"compile_cache: cannot serialize {label or key[:12]} "
                  f"({str(e)[:200]}) — running uncached", file=sys.stderr)
        return compiled, info

    # -- manifest ----------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.cache_dir, "manifest.json")

    def _load_manifest(self) -> dict:
        try:
            with open(self._manifest_path()) as f:
                m = json.load(f)
            return m if isinstance(m, dict) else {}
        except (OSError, ValueError):
            return {}

    def _save_manifest(self, m: dict) -> None:
        tmp = self._manifest_path() + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(m, f, indent=1, sort_keys=True)
            os.replace(tmp, self._manifest_path())
        except OSError:
            pass                              # metadata only — never fatal

    def _record_manifest(self, key, label, compile_secs, nbytes, mesh,
                         compiled=None, extra=None):
        jax_v, jaxlib_v = _versions()
        dev = _mesh_device(mesh)
        m = self._load_manifest()
        m[key] = {"label": label, "compile_secs": round(compile_secs, 2),
                  "bytes": int(nbytes), "jax": jax_v, "jaxlib": jaxlib_v,
                  "platform": getattr(dev, "platform", "?"),
                  "device_kind": getattr(dev, "device_kind", "?"),
                  "created": time.time(), "hits": 0}
        if extra:
            # the key_extra dict that went into the program key, so
            # `scripts/explain_program.py --diff` can name WHICH knob
            # split two entries instead of shrugging at opaque hashes
            m[key]["extra"] = dict(extra)
        if compiled is not None:
            # cost/memory summary taken at write time, so a later cache
            # HIT still tells you what you're running (flops, bytes, HBM
            # estimate) — scripts/explain_program.py prints and diffs it
            cost = program_summary(compiled)
            if cost:
                m[key]["cost"] = cost
        self._save_manifest(m)

    def _bump_manifest(self, key, label):
        m = self._load_manifest()
        if key in m:
            m[key]["hits"] = int(m[key].get("hits", 0)) + 1
            m[key]["last_hit"] = time.time()
            self._save_manifest(m)

    # -- reporting ---------------------------------------------------------

    def describe(self) -> str:
        c = self.counters
        return (f"{c['hits']} hit / {c['misses']} miss / "
                f"{c['deserialize_fallbacks']} deserialize-fallback / "
                f"{c['serialize_unsupported']} unserializable "
                f"(dir={self.cache_dir})")


_DISABLED = CompileCache(None, enabled=False)


def get(cache_dir: Optional[str]) -> CompileCache:
    """Shared per-directory instance (process-wide counters)."""
    if not cache_dir:
        return _DISABLED
    cache_dir = os.path.abspath(cache_dir)
    inst = _INSTANCES.get(cache_dir)
    if inst is None:
        inst = _INSTANCES[cache_dir] = CompileCache(cache_dir)
    return inst


def resolve(config: Optional[dict] = None) -> CompileCache:
    """The one resolution rule every entry point shares: the model/worker
    config key ``compile_cache`` (a path enables, ``False``/``""`` force-
    disables), else the ``THEANOMPI_COMPILE_CACHE`` env var, else off.
    ``aot_cache=False`` in the config force-disables regardless (escape
    hatch: keep lazy first-call jit even with a cache dir configured)."""
    config = config or {}
    if config.get("aot_cache", True) is False:
        return _DISABLED
    if "compile_cache" in config:
        d = config["compile_cache"]
        return get(str(d)) if d else _DISABLED
    return get(os.environ.get(ENV_CACHE_DIR) or None)
