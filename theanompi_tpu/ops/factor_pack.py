"""PowerSGD factor matmuls fused with the collective staging pack.

PowerSGD ships per-leaf low-rank factors (``P = Mp @ Q``, ``Qn = Mpᵀ @ Ph``,
arXiv:1905.13727).  Unfused, every leaf's small matmul lands in its own HBM
buffer and a separate flatten/pad/concat pass assembles the collective's
staging buffer — one extra round-trip per factor per step.  The fused kernel
here emits each factor tile already padded to the staging row alignment, so
the MXU output IS the staging slice: the strategy concatenates the padded
tiles and issues ONE psum for every compressible leaf's factors instead of
one collective per leaf (``parallel/strategies.py`` PowerSGD).

House pattern (docs/design.md §24): pure-jnp oracle :func:`matmul_pack_jnp`
with the identical layout as the non-TPU dispatch target, interpret-mode
equality test, ``vma_of`` for shard_map vma propagation, dispatch gated by
``THEANOMPI_TPU_NO_PALLAS``.  The padded rows are zeros, so a psum over the
staging buffer is elementwise identical to the per-leaf psums it replaces.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_util import dispatch_pallas as _dispatch_pallas
from ._pallas_util import vma_of as _vma_of

# Factor tiles are padded to the fp32 sublane multiple so every slice of the
# concatenated staging buffer stays tile-aligned.
_SUBLANE = 8
# Grid block over the output rows; the contraction dim rides whole in VMEM
# (PowerSGD leaves have cols ≤ a few thousand — far under the VMEM budget).
ROW_BLOCK = 256


def pad_rows(rows: int) -> int:
    """Staging row count for a factor with ``rows`` true rows."""
    return -(-rows // _SUBLANE) * _SUBLANE


def matmul_pack_jnp(m: jnp.ndarray, q: jnp.ndarray,
                    rows_pad: int) -> jnp.ndarray:
    """Oracle: ``m @ q`` zero-padded to ``[rows_pad, rank]`` — the staging
    slice layout the kernel emits directly from the MXU."""
    p = m @ q
    return jnp.pad(p, ((0, rows_pad - p.shape[0]), (0, 0)))


def _make_matmul_pack_kernel(rows: int, block_rows: int):
    def kernel(m_ref, q_ref, out_ref):
        """(block, cols) f32 × (cols, rank) f32 → (block, rank) f32 staging
        tile, rows ≥ the true row count zeroed so the downstream psum of the
        concatenated staging buffer matches the per-leaf psums exactly."""
        j = pl.program_id(0)
        p = jnp.dot(m_ref[:], q_ref[:], preferred_element_type=jnp.float32)
        rid = j * block_rows + jax.lax.broadcasted_iota(
            jnp.int32, (block_rows, 1), 0)
        out_ref[:] = jnp.where(rid < rows, p, 0.0)
    return kernel


@functools.partial(jax.jit, static_argnames=("rows_pad", "interpret"))
def _matmul_pack_pallas(m: jnp.ndarray, q: jnp.ndarray, rows_pad: int,
                        interpret: bool) -> jnp.ndarray:
    rows, cols = m.shape
    rank = q.shape[1]
    block = min(ROW_BLOCK, rows_pad)
    nb = -(-rows_pad // block)
    return pl.pallas_call(
        _make_matmul_pack_kernel(rows, block),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block, cols), lambda j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cols, rank), lambda j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, rank), lambda j: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows_pad, rank), jnp.float32,
                                       vma=_vma_of(m, q)),
        interpret=interpret,
    )(m, q)


def matmul_pack(m: jnp.ndarray, q: jnp.ndarray,
                rows_pad: int | None = None) -> jnp.ndarray:
    """``m [rows, cols] @ q [cols, rank]`` emitted as a zero-padded
    ``[rows_pad, rank]`` staging tile (``rows_pad`` defaults to the sublane
    round-up of ``rows``).  For the Q-side factor pass callers hand in the
    transposed operand (``matmul_pack(Mp.T, Ph, ...)``)."""
    rows = m.shape[0]
    if rows_pad is None:
        rows_pad = pad_rows(rows)
    assert rows_pad >= rows and rows_pad % _SUBLANE == 0, (rows, rows_pad)
    if not _dispatch_pallas():
        return matmul_pack_jnp(m, q, rows_pad)
    return _matmul_pack_pallas(m, q, rows_pad, False)


# pallas_call wrapper → jnp oracle pairing (tpulint ``oracle-pair`` checker).
PALLAS_ORACLES = {
    "_matmul_pack_pallas": "matmul_pack_jnp",
}
