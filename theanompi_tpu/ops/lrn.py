"""Fused LRN (local response normalization) Pallas TPU kernels.

AlexNet's LRN is the hot non-matmul op of the zoo's flagship model
(reference: ``theanompi/models/layers2.py`` LRN over cuDNN/Theano — here the
op itself is re-designed for TPU).  The XLA lowering (band-matrix conv +
elementwise, ``models/layers.py``) materializes fp32 ``x²`` and the band sum
in HBM between fusions; at AlexNet's lrn1 shape (128×55×55×96) that is ~5
array passes forward+backward.  The fused kernels read ``x`` (and ``dy``)
once and write the result once, with the 5-tap cross-channel sum done as a
small matmul against a constant banded matrix on the MXU — the channel dim is
the lane dim, where sliding-window ops are slow but matmuls are native.

Math (β defaults to the AlexNet 0.75):

    d = k + (α/n)·BandSum(x²)         s = d^(−β)         y = x·s
    t = dy·x·s/d
    dx = s·dy − 2·(α/n)·β · x · BandSum(t)      (band window is symmetric)

Dispatch follows ``ops/compress.py``: compiled Pallas on TPU, the jnp
reference (same formula, autodiff'd for bwd) elsewhere and under
``THEANOMPI_TPU_NO_PALLAS=1``; interpret-mode kernels are equality-tested
against the oracle in ``tests/test_lrn_pallas.py``.

**Measured status (TPU, AlexNet lrn1 128×55×55×96 bf16):** this fused kernel
runs 1.44 ms fwd / ~4 ms fwd+bwd, while XLA's band-matrix-conv lowering
(``models/layers.py`` LRN, same math) measures 2.66 ms fwd+bwd — XLA's 1×1
conv + fusion path wins, and in the full AlexNet step the gap widens (9.3 →
18.3 ms/step: ``custom_vjp`` is a fusion barrier and the saved ``x``
residual adds traffic).  So the band-conv stays the default and this kernel
is the selectable alternative (``lrn_impl='pallas'`` model config), kept
honest by the oracle tests.  (A lane-roll variant was also measured: ~1.8×
slower than the in-kernel matmul — cross-lane rolls are expensive; the MXU
band-matmul is the right TPU shape for a channel-window sum.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_util import dispatch_pallas as _dispatch_pallas
from ._pallas_util import vma_of as _vma_of

BLOCK_ROWS = 512       # pixel rows per grid block — fastest of the measured
                       # {256, 512, 1024, 2048} sweep at AlexNet shapes


@functools.lru_cache(maxsize=None)
def _band_np(c: int, n: int) -> np.ndarray:
    half = n // 2
    band = np.zeros((c, c), np.float32)
    for i in range(c):
        band[max(0, i - half):i + half + 1, i] = 1.0
    return band


def _scale_of(d, beta: float):
    """d^(−β) on the VPU — rsqrt composition for the AlexNet β."""
    if beta == 0.75:
        inv = jax.lax.rsqrt(d)
        return inv * jnp.sqrt(inv)
    return jnp.exp(-beta * jnp.log(d))


# ---------------------------------------------------------------------------
# jnp reference (oracle + non-TPU fallback; autodiff provides its bwd)
# ---------------------------------------------------------------------------

def lrn_jnp(x: jnp.ndarray, n: int, k: float, alpha: float,
            beta: float) -> jnp.ndarray:
    """Reference formula, fp32 accumulation, band sum as 1×1 conv.

    This is ALSO the production XLA path (``models/layers.py`` LRN delegates
    here), so the conv runs on the input's native NHWC shape — reshaping to
    a (1, M, 1, C) pseudo-image measures ~6× slower on TPU.
    """
    c = x.shape[-1]
    x4 = x if x.ndim == 4 else x.reshape(1, -1, 1, c)
    sq = jnp.square(x4.astype(jnp.float32))
    ssum = jax.lax.conv_general_dilated(
        sq, jnp.asarray(_band_np(c, n)).reshape(1, 1, c, c),
        (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    d = k + (alpha / n) * ssum
    y4 = (x4.astype(jnp.float32) * _scale_of(d, beta)).astype(x.dtype)
    return y4.reshape(x.shape)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _make_fwd_kernel(n: int, k: float, alpha: float, beta: float):
    def kernel(x_ref, band_ref, y_ref):
        xf = x_ref[:].astype(jnp.float32)
        ssum = jnp.dot(xf * xf, band_ref[:],
                       preferred_element_type=jnp.float32)
        d = k + (alpha / n) * ssum
        y_ref[:] = (xf * _scale_of(d, beta)).astype(y_ref.dtype)
    return kernel


def _make_bwd_kernel(n: int, k: float, alpha: float, beta: float):
    c2b = 2.0 * (alpha / n) * beta

    def kernel(x_ref, dy_ref, band_ref, dx_ref):
        xf = x_ref[:].astype(jnp.float32)
        dyf = dy_ref[:].astype(jnp.float32)
        band = band_ref[:]
        ssum = jnp.dot(xf * xf, band, preferred_element_type=jnp.float32)
        d = k + (alpha / n) * ssum
        s = _scale_of(d, beta)
        t = dyf * xf * s / d
        back = jnp.dot(t, band, preferred_element_type=jnp.float32)
        dx_ref[:] = (s * dyf - c2b * xf * back).astype(dx_ref.dtype)
    return kernel


def _rows_view(x):
    c = x.shape[-1]
    return x.reshape(-1, c), c


@functools.partial(jax.jit,
                   static_argnames=("n", "k", "alpha", "beta", "interpret"))
def _lrn_fwd_pallas(x, n, k, alpha, beta, interpret=False):
    x2d, c = _rows_view(x)
    m = x2d.shape[0]
    band = jnp.asarray(_band_np(c, n))
    y2d = pl.pallas_call(
        _make_fwd_kernel(n, k, alpha, beta),
        grid=(pl.cdiv(m, BLOCK_ROWS),),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, c), lambda j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((c, c), lambda j: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, c), lambda j: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, c), x.dtype, vma=_vma_of(x)),
        interpret=interpret,
    )(x2d, band)
    return y2d.reshape(x.shape)


@functools.partial(jax.jit,
                   static_argnames=("n", "k", "alpha", "beta", "interpret"))
def _lrn_bwd_pallas(x, dy, n, k, alpha, beta, interpret=False):
    x2d, c = _rows_view(x)
    dy2d, _ = _rows_view(dy)
    m = x2d.shape[0]
    band = jnp.asarray(_band_np(c, n))
    spec = pl.BlockSpec((BLOCK_ROWS, c), lambda j: (j, 0),
                        memory_space=pltpu.VMEM)
    dx2d = pl.pallas_call(
        _make_bwd_kernel(n, k, alpha, beta),
        grid=(pl.cdiv(m, BLOCK_ROWS),),
        in_specs=[spec, spec,
                  pl.BlockSpec((c, c), lambda j: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((m, c), x.dtype, vma=_vma_of(x, dy)),
        interpret=interpret,
    )(x2d, dy2d, band)
    return dx2d.reshape(x.shape)


# ---------------------------------------------------------------------------
# custom_vjp wrapper (public API)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _lrn_tpu(x, n, k, alpha, beta):
    return _lrn_fwd_pallas(x, n, k, alpha, beta)


def _lrn_tpu_fwd(x, n, k, alpha, beta):
    return _lrn_fwd_pallas(x, n, k, alpha, beta), x   # residual: x only


def _lrn_tpu_bwd(n, k, alpha, beta, x, dy):
    return (_lrn_bwd_pallas(x, dy, n, k, alpha, beta),)


_lrn_tpu.defvjp(_lrn_tpu_fwd, _lrn_tpu_bwd)


def lrn(x: jnp.ndarray, n: int = 5, k: float = 2.0, alpha: float = 1e-4,
        beta: float = 0.75) -> jnp.ndarray:
    """Fused cross-channel LRN over NHWC (Pallas on TPU, jnp elsewhere)."""
    if _dispatch_pallas():
        return _lrn_tpu(x, n, float(k), float(alpha), float(beta))
    return lrn_jnp(x, n, k, alpha, beta)


# pallas_call wrapper → jnp oracle pairing (tpulint ``oracle-pair`` checker).
# The bwd kernel's oracle is jax.grad of lrn_jnp, so both map to it.
PALLAS_ORACLES = {
    "_lrn_fwd_pallas": "lrn_jnp",
    "_lrn_bwd_pallas": "lrn_jnp",
}
