"""Ring attention — sequence-parallel exact attention over the ICI ring.

Beyond-parity capability (the reference is a CNN data-parallel framework
with no sequence models, SURVEY.md §1/§5): long-context training needs the
sequence dimension sharded across chips, and the TPU-native way to make
exact attention work under that sharding is the ring algorithm (Liu et al.
2023's blockwise formulation): each chip holds one Q/K/V sequence block,
K/V blocks rotate around the ring via ``lax.ppermute``, and a numerically
stable online-softmax accumulator combines the per-block partial attentions
— compute overlaps the neighbor exchange hop by hop, HBM never holds the
full [T, T] score matrix, and the wire cost per chip is one K/V block per
hop riding ICI.

:func:`ring_attention` is written to be traced INSIDE a ``shard_map`` whose
``axis`` shards the sequence dimension (the same pattern as the exchanger
collectives, ``parallel/strategies.py``).  :func:`ring_attention_sharded`
wraps it for direct calls on a sequence mesh.  Exactness vs a single-device
softmax-attention oracle is pinned in ``tests/test_ring_attention.py``,
causal and non-causal, fwd AND grads.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..jax_compat import shard_map as _shard_map

NEG_INF = -1e30


def ring_attention(q, k, v, *, axis: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Exact attention with the sequence dimension sharded over ``axis``.

    Args (per-device shards, inside ``shard_map``):
      q, k, v: ``[B, H, T_local, D]`` — this device's sequence block.
      causal: standard causal masking in GLOBAL positions.
      scale: defaults to ``1/sqrt(D)``.

    Returns ``[B, H, T_local, D]`` — this device's block of the attention
    output, bit-comparable to slicing full attention (up to fp accumulation
    order).
    """
    n = lax.psum(1, axis)
    idx = lax.axis_index(axis)
    b, h, t_loc, d = q.shape
    scale = (1.0 / (d ** 0.5)) if scale is None else scale
    qf = q.astype(jnp.float32) * scale

    q_pos = idx * t_loc + jnp.arange(t_loc)             # global q positions
    perm = [(i, (i + 1) % n) for i in range(n)]

    def block(o, m, l, kj, vj, j):
        """Online-softmax accumulation of one K/V block.  After j forward
        rotations this device holds the block that originated at device
        (idx - j) mod n."""
        src = (idx - j) % n
        k_pos = src * t_loc + jnp.arange(t_loc)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kj.astype(jnp.float32))
        if causal:
            valid = q_pos[:, None] >= k_pos[None, :]    # [Tq, Tk]
            s = jnp.where(valid[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # rows with no valid key yet keep m == NEG_INF; exp(s - m) would be
        # exp(0)=1 on masked entries, so re-zero them explicitly
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(valid[None, None], p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vj.astype(jnp.float32))
        return o, m_new, l

    def hop(carry, j):
        o, m, l, kj, vj = carry
        # rotate BEFORE compute (hops 1..n-1): the local block was consumed
        # outside the scan, so only n-1 exchanges cross ICI in total
        kj = lax.ppermute(kj, axis, perm)
        vj = lax.ppermute(vj, axis, perm)
        o, m, l = block(o, m, l, kj, vj, j)
        return (o, m, l, kj, vj), None

    # derive the zero-init carries from qf so they inherit its FULL set of
    # device-varying mesh axes (on a 2-D data×seq mesh q varies over both;
    # fresh zeros would be device-invariant and fail scan's carry typing)
    o0 = qf * 0.0
    m0 = qf.max(axis=-1) * 0.0 + NEG_INF
    l0 = qf.max(axis=-1) * 0.0
    o0, m0, l0 = block(o0, m0, l0, k, v, 0)             # the local block
    if n > 1:
        (o, m, l, _, _), _ = lax.scan(hop, (o0, m0, l0, k, v),
                                      jnp.arange(1, n))
    else:
        o, m, l = o0, m0, l0
    # causal row 0 of device 0 always has ≥1 valid key (itself), so l > 0
    out = o / l[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, *, axis: str,
                           causal: bool = False,
                           scale: Optional[float] = None):
    """Convenience wrapper: shard ``[B, H, T, D]`` tensors over ``axis`` on
    ``mesh`` (sequence dim) and run :func:`ring_attention` under
    ``shard_map``."""
    spec = P(None, None, axis, None)
    fn = _shard_map(
        partial(ring_attention, axis=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    sh = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    return fn(q, k, v)


def attention_reference(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None):
    """Single-device softmax attention oracle (tests)."""
    d = q.shape[-1]
    scale = (1.0 / (d ** 0.5)) if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        valid = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(valid[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
