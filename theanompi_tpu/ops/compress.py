"""Sign bit-pack/unpack kernels for the compressed exchanger.

TPU-native successor to the reference's in-repo native code: Theano-MPI's
``Exch_asa16``/``Exch_copper16`` compiled inline fp32↔fp16 CUDA kernels at
runtime via ``pycuda.compiler.SourceModule`` to halve wire bandwidth
(SURVEY.md §2.9, items N1/N2).  Here the compression is more aggressive —
1 bit per element.  This module currently ships the portable jnp
implementation (used on CPU tests and as the reference oracle); the Pallas
TPU kernel pair (pack / unpack-accumulate) is the planned hot path and will
slot in behind the same two functions.

Layout contract: input length must be a multiple of :data:`PACK_ALIGN`
(= 1024 = 8 bits × 128 lanes) so both the packed and unpacked views tile
cleanly onto the VPU's (8, 128) registers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# 8 bits/byte × 128 lanes: keeps packed rows lane-aligned on TPU.
PACK_ALIGN = 1024

_POWERS = 2 ** np.arange(8, dtype=np.uint8)  # LSB-first bit order


def pack_signs(c: jnp.ndarray) -> jnp.ndarray:
    """Pack sign bits of ``c`` (>=0 → 1, <0 → 0) into a uint8 vector, 8/byte.

    ``c`` must be 1-D with length % PACK_ALIGN == 0.  Returns [len(c)//8]
    uint8.
    """
    n = c.shape[0]
    assert n % PACK_ALIGN == 0, f"pack_signs needs length % {PACK_ALIGN}, got {n}"
    bits = (c >= 0).astype(jnp.uint8).reshape(n // 8, 8)
    return (bits * _POWERS).sum(axis=1).astype(jnp.uint8)


def unpack_signs(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_signs`: uint8 [m] → float32 [8m] of ±1."""
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def unpack_signs_weighted_sum(all_packed: jnp.ndarray,
                              scales: jnp.ndarray) -> jnp.ndarray:
    """Decode ``[n_workers, m]`` packed sign buffers and return
    ``sum_w scales[w] * signs[w]`` as float32 ``[8m]``.

    This is the decode+accumulate half of the compressed allreduce: each
    worker runs it locally after the all-gather of packed bits, so only bits
    ever cross ICI.
    """
    n_workers, m = all_packed.shape
    bits = (all_packed[:, :, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    signs = bits.astype(jnp.float32) * 2.0 - 1.0          # [w, m, 8]
    weighted = signs * scales[:, None, None]
    return weighted.sum(axis=0).reshape(-1)
