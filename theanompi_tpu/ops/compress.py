"""Sign bit-pack/unpack kernels for the compressed exchanger.

TPU-native successor to the reference's in-repo native code: Theano-MPI's
``Exch_asa16``/``Exch_copper16`` compiled inline fp32↔fp16 CUDA kernels at
runtime via ``pycuda.compiler.SourceModule`` to halve wire bandwidth
(SURVEY.md §2.9, items N1/N2).  Here the compression is more aggressive —
1 bit per element — and the kernels are **Pallas TPU kernels** (the TPU-native
kernel language), with a pure-jnp implementation in the identical bit layout
kept as the numerical oracle and as the dispatch target on non-TPU backends
(and under ``THEANOMPI_TPU_NO_PALLAS=1``).  The kernel unit tests run the
Pallas pair in interpret mode against the oracle bit-for-bit.

Wire format (internal contract between :func:`pack_signs` and the unpackers —
chosen for TPU tiling, NOT byte-compatible with anything external):

* the fp32 input vector ``c`` of length ``n`` (``n % PACK_ALIGN == 0``) is
  viewed as blocks of 256 sublanes × 128 lanes;
* within a block, packed word ``[r, l]`` (r∈[0,8)) collects bit ``b`` from
  input row ``8b + r`` — so every bit plane is a contiguous (8, 128) fp32
  tile and every output tile is a full (8, 128) uint32 tile.  No intra-lane
  shuffles anywhere.
* packed shape: ``[n // 4096, 128]`` uint32 = n/8 bytes on the wire (32×
  smaller than fp32).

Layout contract: input length must be a multiple of :data:`PACK_ALIGN`
(= 32768 = 256 sublanes × 128 lanes) so every Pallas grid block is full.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_util import dispatch_pallas as _dispatch_pallas
from ._pallas_util import vma_of as _vma_of

# 256 fp32 sublanes × 128 lanes per grid block: packs to one (8, 128) uint32
# tile, keeping both sides of the kernel exactly tile-aligned.
BLOCK_ROWS = 256
LANES = 128
PACK_ALIGN = BLOCK_ROWS * LANES          # 32768 elements per grid block
_WORDS_PER_BLOCK = 8                     # uint32 rows produced per block


def _check_len(n: int) -> None:
    assert n % PACK_ALIGN == 0, (
        f"compressed exchange needs length % {PACK_ALIGN} == 0, got {n} "
        "(flatten_tree(pad_to_multiple_of=PACK_ALIGN) upstream)")


# ---------------------------------------------------------------------------
# jnp reference implementations (oracle + fallback)
# ---------------------------------------------------------------------------

def pack_signs_jnp(c: jnp.ndarray) -> jnp.ndarray:
    """Oracle: f32 [n] → uint32 [n//4096, 128] in the wire layout above."""
    n = c.shape[0]
    _check_len(n)
    nb = n // PACK_ALIGN
    bits = (c >= 0).astype(jnp.uint32).reshape(nb, 32, _WORDS_PER_BLOCK, LANES)
    shifts = jnp.arange(32, dtype=jnp.uint32).reshape(1, 32, 1, 1)
    # Bit positions are disjoint across the reduced axis, so sum == OR.
    words = jnp.sum(bits << shifts, axis=1, dtype=jnp.uint32)
    return words.reshape(nb * _WORDS_PER_BLOCK, LANES)


def unpack_signs_jnp(packed: jnp.ndarray) -> jnp.ndarray:
    """Oracle inverse: uint32 [m, 128] → f32 [32·m·128] of ±1."""
    m = packed.shape[0]
    nb = m // _WORDS_PER_BLOCK
    p = packed.reshape(nb, 1, _WORDS_PER_BLOCK, LANES)
    shifts = jnp.arange(32, dtype=jnp.uint32).reshape(1, 32, 1, 1)
    bits = (p >> shifts) & jnp.uint32(1)
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def unpack_signs_weighted_sum_jnp(all_packed: jnp.ndarray,
                                  scales: jnp.ndarray) -> jnp.ndarray:
    """Oracle: decode [w, m, 128] packed buffers → Σ_w scales[w]·signs[w]."""
    w = all_packed.shape[0]
    decoded = jax.vmap(unpack_signs_jnp)(all_packed)       # [w, n]
    return jnp.sum(decoded * scales.reshape(w, 1), axis=0)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _pack_kernel(x_ref, out_ref):
    """(256, 128) f32 block → (8, 128) uint32 block.

    Bit plane b is the contiguous fp32 tile rows [8b, 8b+8); planes are OR'd
    together after shifting — pure VPU work on full (8, 128) registers.
    """
    word = jnp.zeros((_WORDS_PER_BLOCK, LANES), jnp.uint32)
    for b in range(32):
        plane = x_ref[8 * b:8 * (b + 1), :]
        word = word | ((plane >= 0).astype(jnp.uint32) << np.uint32(b))
    out_ref[:] = word


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pack_pallas(x2d: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    nb = x2d.shape[0] // BLOCK_ROWS
    return pl.pallas_call(
        _pack_kernel,
        grid=(nb,),
        in_specs=[pl.BlockSpec((BLOCK_ROWS, LANES), lambda j: (j, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_WORDS_PER_BLOCK, LANES), lambda j: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * _WORDS_PER_BLOCK, LANES),
                                       jnp.uint32, vma=_vma_of(x2d)),
        interpret=interpret,
    )(x2d)


def _make_unpack_wsum_kernel(n_workers: int):
    def kernel(packed_ref, scales_ref, out_ref):
        """packed (W, 8, 128) u32 + scales (W,) → (256, 128) f32 of
        Σ_w scale_w · sign_w  (decode fused with the weighted accumulate, so
        the fp32 expansion never round-trips through HBM)."""
        total = jnp.float32(0.0)
        for w in range(n_workers):
            total = total + scales_ref[w]
        for b in range(32):
            acc = jnp.zeros((_WORDS_PER_BLOCK, LANES), jnp.float32)
            for w in range(n_workers):
                bits = (packed_ref[w] >> np.uint32(b)) & np.uint32(1)
                acc = acc + bits.astype(jnp.float32) * (2.0 * scales_ref[w])
            # Σ scale·(2·bit − 1) = Σ 2·scale·bit − Σ scale
            out_ref[8 * b:8 * (b + 1), :] = acc - total
    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def _unpack_wsum_pallas(all_packed: jnp.ndarray, scales: jnp.ndarray,
                        interpret: bool) -> jnp.ndarray:
    w, m, _ = all_packed.shape
    nb = m // _WORDS_PER_BLOCK
    kernel = _make_unpack_wsum_kernel(w)
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((w, _WORDS_PER_BLOCK, LANES), lambda j: (0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, LANES), lambda j: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * BLOCK_ROWS, LANES), jnp.float32,
                                       vma=_vma_of(all_packed, scales)),
        interpret=interpret,
    )(all_packed, scales)


# ---------------------------------------------------------------------------
# Public API (dispatching)
# ---------------------------------------------------------------------------

def pack_signs(c: jnp.ndarray) -> jnp.ndarray:
    """Pack sign bits of ``c`` (>=0 → 1, <0 → 0), 32 per uint32 word.

    ``c`` must be 1-D with length % PACK_ALIGN == 0.  Returns
    ``[len(c)//4096, 128]`` uint32 (= len(c)/8 bytes on the wire).
    """
    n = c.shape[0]
    _check_len(n)
    if not _dispatch_pallas():
        return pack_signs_jnp(c)
    return _pack_pallas(c.reshape(n // LANES, LANES), False)


def unpack_signs(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_signs`: uint32 [m, 128] → f32 [32·m·128] of ±1."""
    if not _dispatch_pallas():
        return unpack_signs_jnp(packed)
    one = jnp.ones((1,), jnp.float32)
    return _unpack_wsum_pallas(packed[None], one, False).reshape(-1)


def unpack_signs_weighted_sum(all_packed: jnp.ndarray,
                              scales: jnp.ndarray) -> jnp.ndarray:
    """Decode ``[n_workers, m, 128]`` packed sign buffers and return
    ``sum_w scales[w] * signs[w]`` as float32 ``[32·m·128]``.

    This is the decode+accumulate half of the compressed allreduce: each
    worker runs it locally after the all-gather of packed bits, so only bits
    ever cross ICI.
    """
    if not _dispatch_pallas():
        return unpack_signs_weighted_sum_jnp(all_packed, scales)
    return _unpack_wsum_pallas(
        all_packed, scales.astype(jnp.float32), False).reshape(-1)
