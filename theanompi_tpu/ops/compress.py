"""Compression kernels for the compressed exchanger (onebit + topk).

TPU-native successor to the reference's in-repo native code: Theano-MPI's
``Exch_asa16``/``Exch_copper16`` compiled inline fp32↔fp16 CUDA kernels at
runtime via ``pycuda.compiler.SourceModule`` to halve wire bandwidth
(SURVEY.md §2.9, items N1/N2).  Here the compression is more aggressive —
1 bit per element — and the kernels are **Pallas TPU kernels** (the TPU-native
kernel language), with a pure-jnp implementation in the identical bit layout
kept as the numerical oracle and as the dispatch target on non-TPU backends
(and under ``THEANOMPI_TPU_NO_PALLAS=1``).  The kernel unit tests run the
Pallas pair in interpret mode against the oracle bit-for-bit.

Beyond the original sign pack/unpack pair, this module carries the fused
single-pass pipelines (docs/design.md §24):

* **onebit encode** (:func:`pack_signs_encode`): per 256×128 block, read the
  gradient and the error state once, form ``c = flat + state`` in VMEM, and
  emit BOTH the packed sign tile and ``|c|`` — ``c`` itself never exists in
  HBM.  The follow-up :func:`signed_residual` turns ``|c|`` + packed bits +
  the scalar scale into the new error state ``c − scale·sign(c)`` in one more
  pass (bit-identical to the unfused formula; see the oracle's docstring).
* **onebit decode** (:func:`unpack_signs_weighted_mean`): the decode+weighted
  accumulate with the ``/size`` mean folded into the per-worker scales, so the
  full-length division pass disappears.
* **topk encode/decode** (:func:`topk_encode` / :func:`topk_decode`): chunk-row
  kernels fusing the |c| top-k select, bf16 value cast, int16 offset emit and
  in-place residual write (encode), and the expansion of every worker's
  (vals, idx) rows into the dense chunk row block-locally in VMEM (decode),
  replacing the serialized HBM scatter XLA makes of ``.at[idx].add``.

Every ``pl.pallas_call`` wrapper here is paired with its jnp oracle in
:data:`PALLAS_ORACLES`; the tpulint ``oracle-pair`` checker enforces the
pairing and the existence of an equality test.

Wire format (internal contract between :func:`pack_signs` and the unpackers —
chosen for TPU tiling, NOT byte-compatible with anything external):

* the fp32 input vector ``c`` of length ``n`` (``n % PACK_ALIGN == 0``) is
  viewed as blocks of 256 sublanes × 128 lanes;
* within a block, packed word ``[r, l]`` (r∈[0,8)) collects bit ``b`` from
  input row ``8b + r`` — so every bit plane is a contiguous (8, 128) fp32
  tile and every output tile is a full (8, 128) uint32 tile.  No intra-lane
  shuffles anywhere.
* packed shape: ``[n // 4096, 128]`` uint32 = n/8 bytes on the wire (32×
  smaller than fp32).

Layout contract: input length must be a multiple of :data:`PACK_ALIGN`
(= 32768 = 256 sublanes × 128 lanes) so every Pallas grid block is full.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._pallas_util import dispatch_pallas as _dispatch_pallas
from ._pallas_util import vma_of as _vma_of

# 256 fp32 sublanes × 128 lanes per grid block: packs to one (8, 128) uint32
# tile, keeping both sides of the kernel exactly tile-aligned.
BLOCK_ROWS = 256
LANES = 128
PACK_ALIGN = BLOCK_ROWS * LANES          # 32768 elements per grid block
_WORDS_PER_BLOCK = 8                     # uint32 rows produced per block


def _check_len(n: int) -> None:
    assert n % PACK_ALIGN == 0, (
        f"compressed exchange needs length % {PACK_ALIGN} == 0, got {n} "
        "(flatten_tree(pad_to_multiple_of=PACK_ALIGN) upstream)")


# ---------------------------------------------------------------------------
# jnp reference implementations (oracle + fallback)
# ---------------------------------------------------------------------------

def pack_signs_jnp(c: jnp.ndarray) -> jnp.ndarray:
    """Oracle: f32 [n] → uint32 [n//4096, 128] in the wire layout above."""
    n = c.shape[0]
    _check_len(n)
    nb = n // PACK_ALIGN
    bits = (c >= 0).astype(jnp.uint32).reshape(nb, 32, _WORDS_PER_BLOCK, LANES)
    shifts = jnp.arange(32, dtype=jnp.uint32).reshape(1, 32, 1, 1)
    # Bit positions are disjoint across the reduced axis, so sum == OR.
    words = jnp.sum(bits << shifts, axis=1, dtype=jnp.uint32)
    return words.reshape(nb * _WORDS_PER_BLOCK, LANES)


def unpack_signs_jnp(packed: jnp.ndarray) -> jnp.ndarray:
    """Oracle inverse: uint32 [m, 128] → f32 [32·m·128] of ±1."""
    m = packed.shape[0]
    nb = m // _WORDS_PER_BLOCK
    p = packed.reshape(nb, 1, _WORDS_PER_BLOCK, LANES)
    shifts = jnp.arange(32, dtype=jnp.uint32).reshape(1, 32, 1, 1)
    bits = (p >> shifts) & jnp.uint32(1)
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)


def unpack_signs_weighted_sum_jnp(all_packed: jnp.ndarray,
                                  scales: jnp.ndarray) -> jnp.ndarray:
    """Oracle: decode [w, m, 128] packed buffers → Σ_w scales[w]·signs[w]."""
    w = all_packed.shape[0]
    decoded = jax.vmap(unpack_signs_jnp)(all_packed)       # [w, n]
    return jnp.sum(decoded * scales.reshape(w, 1), axis=0)


def pack_signs_encode_jnp(flat: jnp.ndarray, state: jnp.ndarray):
    """Oracle for the fused onebit encode: ``c = flat + state`` →
    (packed signs of c, |c|).  Same packed bit layout as
    :func:`pack_signs_jnp` applied to the materialized sum."""
    c = flat + state
    return pack_signs_jnp(c), jnp.abs(c)


def signed_residual_jnp(absc: jnp.ndarray, packed: jnp.ndarray,
                        scale: jnp.ndarray) -> jnp.ndarray:
    """Oracle for the fused residual: reconstruct ``c − scale·sign(c)`` from
    ``|c|`` and the packed sign bits.

    Bit-exact equivalence with the unfused ``c − scale·sign(where(c==0,1,c))``
    (the packed bit for c == 0 is 1, matching that ``where``):

    * c ≥ 0 (bit 1): ``c − scale·(+1) = c ⊖ scale = |c| ⊖ scale``  (|c| = c).
    * c < 0 (bit 0): ``c − scale·(−1) = c ⊕ scale = scale ⊖ |c|`` — IEEE
      ``x ⊖ y`` is ``x ⊕ (−y)`` with an exact sign flip, and |c| = −c exactly.
    """
    sign_pos = unpack_signs_jnp(packed) > 0
    return jnp.where(sign_pos, absc - scale, scale - absc)


def unpack_signs_weighted_mean_jnp(all_packed: jnp.ndarray,
                                   scales: jnp.ndarray,
                                   size: int) -> jnp.ndarray:
    """Oracle: decode + weighted accumulate with the ``/size`` mean folded
    into the scales — ``Σ_w (scales[w]/size)·signs[w]``.  The full-length
    division pass of the unfused ``sum/size`` becomes a [w]-length one."""
    return unpack_signs_weighted_sum_jnp(all_packed, scales / jnp.float32(size))


def topk_encode_jnp(c2: jnp.ndarray, k: int):
    """Oracle for the fused topk encode: per chunk row of ``c2`` [rows, chunk]
    select the k largest-|·| entries, cast to the wire dtypes, and write the
    bf16 rounding residual back in place.

    Returns ``(wire_vals bf16 [rows, k], wire_idx int16 [rows, k],
    new_c2 f32 [rows, chunk])``.  Tie-break follows ``lax.top_k``: equal
    magnitudes pick the lower index first.
    """
    rows = c2.shape[0]
    _, idx = jax.lax.top_k(jnp.abs(c2), k)                 # [rows, k]
    vals = jnp.take_along_axis(c2, idx, axis=1)            # f32 [rows, k]
    wire_vals = vals.astype(jnp.bfloat16)
    wire_idx = idx.astype(jnp.int16)
    residual = vals - wire_vals.astype(jnp.float32)
    r = jnp.arange(rows)[:, None]
    new_c2 = c2.at[r, idx].set(residual)
    return wire_vals, wire_idx, new_c2


def topk_decode_jnp(all_vals: jnp.ndarray, all_idx: jnp.ndarray,
                    chunk: int, size: int = 1) -> jnp.ndarray:
    """Oracle for the fused topk decode: expand every worker's (vals, idx)
    chunk rows into the dense vector — dense[r·chunk + idx] += val summed
    over workers, divided by ``size`` (the worker mean folded into the
    decode so no full-length division pass follows; ``acc / size`` per
    element is bit-identical to dividing the assembled dense vector).
    [w, rows, k] bf16/int16 → f32 [rows·chunk]."""
    w, rows, k = all_vals.shape
    base = (jnp.arange(rows, dtype=jnp.int32) * chunk).reshape(1, rows, 1)
    gidx = all_idx.astype(jnp.int32) + base                # [w, rows, k]
    dense = jnp.zeros((rows * chunk,), jnp.float32)
    dense = dense.at[gidx.reshape(-1)].add(
        all_vals.astype(jnp.float32).reshape(-1))
    return dense / jnp.float32(size) if size != 1 else dense


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _pack_kernel(x_ref, out_ref):
    """(256, 128) f32 block → (8, 128) uint32 block.

    Bit plane b is the contiguous fp32 tile rows [8b, 8b+8); planes are OR'd
    together after shifting — pure VPU work on full (8, 128) registers.
    """
    word = jnp.zeros((_WORDS_PER_BLOCK, LANES), jnp.uint32)
    for b in range(32):
        plane = x_ref[8 * b:8 * (b + 1), :]
        word = word | ((plane >= 0).astype(jnp.uint32) << np.uint32(b))
    out_ref[:] = word


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pack_pallas(x2d: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    nb = x2d.shape[0] // BLOCK_ROWS
    return pl.pallas_call(
        _pack_kernel,
        grid=(nb,),
        in_specs=[pl.BlockSpec((BLOCK_ROWS, LANES), lambda j: (j, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_WORDS_PER_BLOCK, LANES), lambda j: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * _WORDS_PER_BLOCK, LANES),
                                       jnp.uint32, vma=_vma_of(x2d)),
        interpret=interpret,
    )(x2d)


def _make_unpack_wsum_kernel(n_workers: int):
    def kernel(packed_ref, scales_ref, out_ref):
        """packed (W, 8, 128) u32 + scales (W,) → (256, 128) f32 of
        Σ_w scale_w · sign_w  (decode fused with the weighted accumulate, so
        the fp32 expansion never round-trips through HBM)."""
        total = jnp.float32(0.0)
        for w in range(n_workers):
            total = total + scales_ref[w]
        for b in range(32):
            acc = jnp.zeros((_WORDS_PER_BLOCK, LANES), jnp.float32)
            for w in range(n_workers):
                bits = (packed_ref[w] >> np.uint32(b)) & np.uint32(1)
                acc = acc + bits.astype(jnp.float32) * (2.0 * scales_ref[w])
            # Σ scale·(2·bit − 1) = Σ 2·scale·bit − Σ scale
            out_ref[8 * b:8 * (b + 1), :] = acc - total
    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def _unpack_wsum_pallas(all_packed: jnp.ndarray, scales: jnp.ndarray,
                        interpret: bool) -> jnp.ndarray:
    w, m, _ = all_packed.shape
    nb = m // _WORDS_PER_BLOCK
    kernel = _make_unpack_wsum_kernel(w)
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((w, _WORDS_PER_BLOCK, LANES), lambda j: (0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, LANES), lambda j: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * BLOCK_ROWS, LANES), jnp.float32,
                                       vma=_vma_of(all_packed, scales)),
        interpret=interpret,
    )(all_packed, scales)


def _encode_kernel(flat_ref, state_ref, packed_ref, abs_ref):
    """(256, 128) f32 flat + state blocks → (8, 128) u32 packed + (256, 128)
    f32 |c|.  ``c = flat + state`` lives only in VMEM registers: the fused
    encode reads the error-fed vector once and never writes ``c`` to HBM."""
    word = jnp.zeros((_WORDS_PER_BLOCK, LANES), jnp.uint32)
    for b in range(32):
        c = flat_ref[8 * b:8 * (b + 1), :] + state_ref[8 * b:8 * (b + 1), :]
        word = word | ((c >= 0).astype(jnp.uint32) << np.uint32(b))
        abs_ref[8 * b:8 * (b + 1), :] = jnp.abs(c)
    packed_ref[:] = word


@functools.partial(jax.jit, static_argnames=("interpret",))
def _encode_pallas(flat2d: jnp.ndarray, state2d: jnp.ndarray,
                   interpret: bool):
    nb = flat2d.shape[0] // BLOCK_ROWS
    vma = _vma_of(flat2d, state2d)
    block_in = pl.BlockSpec((BLOCK_ROWS, LANES), lambda j: (j, 0),
                            memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _encode_kernel,
        grid=(nb,),
        in_specs=[block_in, block_in],
        out_specs=[
            pl.BlockSpec((_WORDS_PER_BLOCK, LANES), lambda j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb * _WORDS_PER_BLOCK, LANES), jnp.uint32,
                                 vma=vma),
            jax.ShapeDtypeStruct((nb * BLOCK_ROWS, LANES), jnp.float32,
                                 vma=vma),
        ],
        interpret=interpret,
    )(flat2d, state2d)


def _residual_kernel(abs_ref, packed_ref, scale_ref, out_ref):
    """(256, 128) f32 |c| + (8, 128) u32 packed + SMEM scale →
    (256, 128) f32 residual ``c − scale·sign(c)``, recovered branch-free as
    ``where(bit, |c| − scale, scale − |c|)`` (bit-exact; see the oracle)."""
    scale = scale_ref[0]
    for b in range(32):
        bit = (packed_ref[:] >> np.uint32(b)) & np.uint32(1)
        a = abs_ref[8 * b:8 * (b + 1), :]
        out_ref[8 * b:8 * (b + 1), :] = jnp.where(bit == 1, a - scale,
                                                  scale - a)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _residual_pallas(abs2d: jnp.ndarray, packed: jnp.ndarray,
                     scale: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    nb = abs2d.shape[0] // BLOCK_ROWS
    return pl.pallas_call(
        _residual_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_WORDS_PER_BLOCK, LANES), lambda j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, LANES), lambda j: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * BLOCK_ROWS, LANES), jnp.float32,
                                       vma=_vma_of(abs2d, packed, scale)),
        interpret=interpret,
    )(abs2d, packed, scale.reshape(1).astype(jnp.float32))


def _make_topk_encode_kernel(k: int, chunk: int):
    def kernel(c_ref, vals_ref, idx_ref, state_ref):
        """One chunk row per grid step: iterative argmax over |row| (first
        max index == lax.top_k's lower-index tie-break), emitting the bf16
        wire value, int16 chunk-local offset, and the in-place bf16 rounding
        residual — all from one VMEM-resident copy of the row."""
        lanes = jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)

        def body(j, carry):
            cur, amask = carry
            m = jnp.max(amask)
            # First lane attaining the max: ties pick the lowest index,
            # matching lax.top_k's ordering in the oracle.
            idx = jnp.min(jnp.where(amask == m, lanes, chunk))
            v = jnp.sum(jnp.where(lanes == idx, cur, 0.0))
            wv = v.astype(jnp.bfloat16)
            pl.store(vals_ref, (0, pl.dslice(j, 1)), wv.reshape(1, 1))
            pl.store(idx_ref, (0, pl.dslice(j, 1)),
                     idx.astype(jnp.int16).reshape(1, 1))
            hit = lanes == idx
            cur = jnp.where(hit, v - wv.astype(jnp.float32), cur)
            # Selected lanes leave the running argmax for good: |·| ≥ 0, so
            # −1 can never win again (relying on the residual being small
            # would diverge from top_k on all-zero rows).
            amask = jnp.where(hit, jnp.float32(-1.0), amask)
            return cur, amask

        row = c_ref[:]
        cur, _ = jax.lax.fori_loop(0, k, body, (row, jnp.abs(row)))
        state_ref[:] = cur
    return kernel


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def _topk_encode_pallas(c2: jnp.ndarray, k: int, interpret: bool):
    rows, chunk = c2.shape
    vma = _vma_of(c2)
    row_spec = lambda shape: pl.BlockSpec((1, shape), lambda j: (j, 0),
                                          memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _make_topk_encode_kernel(k, chunk),
        grid=(rows,),
        in_specs=[row_spec(chunk)],
        out_specs=[row_spec(k), row_spec(k), row_spec(chunk)],
        out_shape=[
            jax.ShapeDtypeStruct((rows, k), jnp.bfloat16, vma=vma),
            jax.ShapeDtypeStruct((rows, k), jnp.int16, vma=vma),
            jax.ShapeDtypeStruct((rows, chunk), jnp.float32, vma=vma),
        ],
        interpret=interpret,
    )(c2)


def _make_topk_decode_kernel(n_workers: int, k: int, chunk: int, size: int):
    def kernel(vals_ref, idx_ref, out_ref):
        """All workers' (vals, idx) for one chunk row → the dense row,
        accumulated block-locally in VMEM in (worker asc, slot asc) order —
        the same per-element order as the flattened ``.at[gidx].add`` scatter
        the oracle performs, with no serialized HBM scatter anywhere.  The
        ``/size`` worker mean rides the final store."""
        lanes = jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        acc = jnp.zeros((1, chunk), jnp.float32)
        for w in range(n_workers):
            def body(j, acc, w=w):
                v = pl.load(vals_ref, (w, 0, pl.dslice(j, 1)))
                i = pl.load(idx_ref, (w, 0, pl.dslice(j, 1)))
                hit = lanes == i.astype(jnp.int32).reshape(1, 1)
                return acc + jnp.where(hit, v.astype(jnp.float32), 0.0)
            acc = jax.lax.fori_loop(0, k, body, acc)
        out_ref[:] = acc / jnp.float32(size) if size != 1 else acc
    return kernel


@functools.partial(jax.jit, static_argnames=("chunk", "size", "interpret"))
def _topk_decode_pallas(all_vals: jnp.ndarray, all_idx: jnp.ndarray,
                        chunk: int, size: int, interpret: bool) -> jnp.ndarray:
    w, rows, k = all_vals.shape
    wire_spec = pl.BlockSpec((w, 1, k), lambda j: (0, j, 0),
                             memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _make_topk_decode_kernel(w, k, chunk, size),
        grid=(rows,),
        in_specs=[wire_spec, wire_spec],
        out_specs=pl.BlockSpec((1, chunk), lambda j: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, chunk), jnp.float32,
                                       vma=_vma_of(all_vals, all_idx)),
        interpret=interpret,
    )(all_vals, all_idx)


# ---------------------------------------------------------------------------
# Public API (dispatching)
# ---------------------------------------------------------------------------

def pack_signs(c: jnp.ndarray) -> jnp.ndarray:
    """Pack sign bits of ``c`` (>=0 → 1, <0 → 0), 32 per uint32 word.

    ``c`` must be 1-D with length % PACK_ALIGN == 0.  Returns
    ``[len(c)//4096, 128]`` uint32 (= len(c)/8 bytes on the wire).
    """
    n = c.shape[0]
    _check_len(n)
    if not _dispatch_pallas():
        return pack_signs_jnp(c)
    return _pack_pallas(c.reshape(n // LANES, LANES), False)


def unpack_signs(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_signs`: uint32 [m, 128] → f32 [32·m·128] of ±1."""
    if not _dispatch_pallas():
        return unpack_signs_jnp(packed)
    one = jnp.ones((1,), jnp.float32)
    return _unpack_wsum_pallas(packed[None], one, False).reshape(-1)


def unpack_signs_weighted_sum(all_packed: jnp.ndarray,
                              scales: jnp.ndarray) -> jnp.ndarray:
    """Decode ``[n_workers, m, 128]`` packed sign buffers and return
    ``sum_w scales[w] * signs[w]`` as float32 ``[32·m·128]``.

    This is the decode+accumulate half of the compressed allreduce: each
    worker runs it locally after the all-gather of packed bits, so only bits
    ever cross ICI.
    """
    if not _dispatch_pallas():
        return unpack_signs_weighted_sum_jnp(all_packed, scales)
    return _unpack_wsum_pallas(
        all_packed, scales.astype(jnp.float32), False).reshape(-1)


def pack_signs_encode(flat: jnp.ndarray, state: jnp.ndarray):
    """Fused onebit encode: ``c = flat + state`` formed in VMEM, returning
    ``(packed signs of c, |c|)`` — one read of each input, no HBM copy of
    ``c``.  Both 1-D inputs must share a length % PACK_ALIGN == 0."""
    n = flat.shape[0]
    _check_len(n)
    if not _dispatch_pallas():
        return pack_signs_encode_jnp(flat, state)
    packed, abs2d = _encode_pallas(flat.reshape(n // LANES, LANES),
                                   state.reshape(n // LANES, LANES), False)
    return packed, abs2d.reshape(-1)


def signed_residual(absc: jnp.ndarray, packed: jnp.ndarray,
                    scale: jnp.ndarray) -> jnp.ndarray:
    """New onebit error state ``c − scale·sign(c)`` from ``|c|`` + packed
    sign bits + the scalar scale (bit-exact vs the unfused formula)."""
    n = absc.shape[0]
    _check_len(n)
    if not _dispatch_pallas():
        return signed_residual_jnp(absc, packed, scale)
    return _residual_pallas(absc.reshape(n // LANES, LANES), packed,
                            scale, False).reshape(-1)


def unpack_signs_weighted_mean(all_packed: jnp.ndarray, scales: jnp.ndarray,
                               size: int) -> jnp.ndarray:
    """Decode ``[n_workers, m, 128]`` packed buffers into the worker-mean
    ``Σ_w (scales[w]/size)·signs[w]`` — the ``/size`` folded into the [w]
    scale vector so no full-length division pass follows the decode."""
    ws = scales.astype(jnp.float32) / jnp.float32(size)
    if not _dispatch_pallas():
        return unpack_signs_weighted_sum_jnp(all_packed, ws)
    return _unpack_wsum_pallas(all_packed, ws, False).reshape(-1)


def topk_encode(c2: jnp.ndarray, k: int):
    """Fused topk encode of ``c2`` [rows, chunk]: per chunk row, the k
    largest-|·| entries as ``(bf16 vals, int16 offsets)`` plus the new error
    state with the bf16 rounding residual written in place."""
    if not _dispatch_pallas():
        return topk_encode_jnp(c2, k)
    return _topk_encode_pallas(c2, k, False)


def topk_decode(all_vals: jnp.ndarray, all_idx: jnp.ndarray,
                chunk: int, size: int = 1) -> jnp.ndarray:
    """Fused topk decode: all workers' ``[w, rows, k]`` wire rows expanded
    and summed into the dense f32 ``[rows·chunk]`` vector block-locally (no
    serialized HBM scatter), with the ``/size`` worker mean folded in."""
    if not _dispatch_pallas():
        return topk_decode_jnp(all_vals, all_idx, chunk, size)
    return _topk_decode_pallas(all_vals, all_idx, chunk, size,
                               False).reshape(-1)


# pallas_call wrapper → jnp oracle pairing, enforced by the tpulint
# ``oracle-pair`` checker (every wrapper must appear here, every oracle must
# be defined in this module, and a test must reference both).
PALLAS_ORACLES = {
    "_pack_pallas": "pack_signs_jnp",
    "_unpack_wsum_pallas": "unpack_signs_weighted_sum_jnp",
    "_encode_pallas": "pack_signs_encode_jnp",
    "_residual_pallas": "signed_residual_jnp",
    "_topk_encode_pallas": "topk_encode_jnp",
    "_topk_decode_pallas": "topk_decode_jnp",
}
