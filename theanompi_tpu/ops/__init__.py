"""Custom TPU ops (Pallas kernels + jnp fallbacks)."""
