"""Shared dispatch/vma helpers for the Pallas op modules."""

from __future__ import annotations

import os

import jax


def dispatch_pallas() -> bool:
    """Compiled Pallas on TPU; elsewhere the jnp oracle (same semantics,
    equality-tested) — interpret-mode Pallas can't run inside shard_map's
    vma-checked trace, so it is reserved for the direct kernel tests.
    ``THEANOMPI_TPU_NO_PALLAS=1`` forces the oracle everywhere."""
    if os.environ.get("THEANOMPI_TPU_NO_PALLAS", "0") == "1":
        return False
    return jax.default_backend() == "tpu"


def vma_of(*xs) -> frozenset:
    """Union of the operands' varying-manual-axes, so pallas_call outputs
    carry the right vma when traced inside ``shard_map(check_vma=True)``."""
    vma: frozenset = frozenset()
    for x in xs:
        vma = vma | getattr(jax.typeof(x), "vma", frozenset())
    return vma
