"""Shared dispatch/vma helpers for the Pallas op modules."""

from __future__ import annotations

import os

import jax


# Memoized dispatch decision: the env lookup + backend probe run once per
# process, not once per op call (the compressed exchanger consults this on
# every encode/decode inside traced code, where a surprise os.environ read
# per call is pure overhead).  None = not yet decided.
_DISPATCH_MEMO: bool | None = None


def dispatch_pallas() -> bool:
    """Compiled Pallas on TPU; elsewhere the jnp oracle (same semantics,
    equality-tested) — interpret-mode Pallas can't run inside shard_map's
    vma-checked trace, so it is reserved for the direct kernel tests.
    ``THEANOMPI_TPU_NO_PALLAS=1`` forces the oracle everywhere.

    The decision is cached per process; tests that flip the env var must
    call :func:`reset_dispatch_cache` after ``monkeypatch.setenv``.
    """
    global _DISPATCH_MEMO
    if _DISPATCH_MEMO is None:
        if os.environ.get("THEANOMPI_TPU_NO_PALLAS", "0") == "1":
            _DISPATCH_MEMO = False
        else:
            _DISPATCH_MEMO = jax.default_backend() == "tpu"
    return _DISPATCH_MEMO


def reset_dispatch_cache() -> None:
    """Drop the memoized dispatch decision (for tests that toggle
    ``THEANOMPI_TPU_NO_PALLAS`` mid-process)."""
    global _DISPATCH_MEMO
    _DISPATCH_MEMO = None


def vma_of(*xs) -> frozenset:
    """Union of the operands' varying-manual-axes, so pallas_call outputs
    carry the right vma when traced inside ``shard_map(check_vma=True)``."""
    vma: frozenset = frozenset()
    for x in xs:
        vma = vma | getattr(jax.typeof(x), "vma", frozenset())
    return vma
