"""Version-portability shims: ``shard_map`` and async collectives.

The framework is written against current jax (``jax.shard_map`` with the
vma varying-axes type system).  The container this repo grows in may pin
an older release (observed: 0.4.37) where shard_map still lives in
``jax.experimental.shard_map`` and replication is tracked by the legacy
``check_rep`` pass instead of vma.  Every shard_map call site goes
through this one shim so the SPMD machinery imports and runs on both.

On the legacy path ``check_rep=False``: the old replication checker
predates the vma typing this code is written for (per-worker varying
scan carries, ``steps.anchor_invariant``) and rejects valid programs
here; on current jax the vma system supersedes it anyway.

**Async collective start/done pairs** (the bucketed-overlap wire,
``parallel/buckets.py``): some jaxlibs expose an explicit async
collective surface (``lax.psum_start``/``psum_done``-shaped APIs that
return an in-flight token); most — including this one — do not, and rely
on XLA's latency-hiding scheduler to convert independent collectives to
``<op>-start``/``<op>-done`` HLO pairs itself.  The shims below give the
exchange path ONE calling convention for both worlds:

* when the running jaxlib exposes the async API, ``<x>_start`` returns
  its real in-flight ticket and ``<x>_done`` awaits it;
* otherwise (the sync fallback) ``<x>_start`` issues the plain
  collective eagerly — the ticket IS the result — and ``<x>_done``
  unwraps it.  Scheduling-wise nothing is lost: each bucket is still its
  own independent collective for the latency-hiding scheduler to
  overlap with the backward pass.

Discipline contract (enforced by tpulint's collective-discipline
checker): every ``<x>_start`` call's ticket must reach a matching
``<x>_done`` in the same scope — a dropped ticket is a leaked in-flight
collective the day a real async surface binds.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
from jax import lax


def shard_map(f, *, mesh, in_specs, out_specs):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


# -- async collective start/done ---------------------------------------------

# True when the running jaxlib exposes a real async start/done surface;
# the sync fallback below is used otherwise (0.4.x has none).
HAS_ASYNC_COLLECTIVES = all(
    hasattr(lax, n) for n in ("psum_start", "psum_done"))


class _SyncTicket(NamedTuple):
    """Sync-fallback in-flight token: the collective already ran eagerly,
    the ticket carries its result to the paired ``<x>_done``."""

    value: Any


def psum_start(x, axis_name):
    """Begin one bucket's cross-worker sum; returns an in-flight ticket
    for :func:`psum_done`."""
    if HAS_ASYNC_COLLECTIVES:
        return lax.psum_start(x, axis_name)
    return _SyncTicket(lax.psum(x, axis_name))


def psum_done(ticket):
    """Await one :func:`psum_start` ticket and return the reduced value."""
    if HAS_ASYNC_COLLECTIVES:
        return lax.psum_done(ticket)
    return ticket.value


def all_gather_start(x, axis_name):
    """Begin one bucket's all-gather (compressed wires ship packed
    buckets); returns an in-flight ticket for :func:`all_gather_done`."""
    if hasattr(lax, "all_gather_start"):
        return lax.all_gather_start(x, axis_name)
    return _SyncTicket(lax.all_gather(x, axis_name))


def all_gather_done(ticket):
    """Await one :func:`all_gather_start` ticket."""
    if hasattr(lax, "all_gather_done"):
        return lax.all_gather_done(ticket)
    return ticket.value


def ppermute_start(x, axis_name, perm):
    """Begin one bucket's peer-to-peer permute (GoSGD gossip payloads);
    returns an in-flight ticket for :func:`ppermute_done`."""
    if hasattr(lax, "ppermute_start"):
        return lax.ppermute_start(x, axis_name, perm)
    return _SyncTicket(lax.ppermute(x, axis_name, perm))


def ppermute_done(ticket):
    """Await one :func:`ppermute_start` ticket."""
    if hasattr(lax, "ppermute_done"):
        return lax.ppermute_done(ticket)
    return ticket.value
