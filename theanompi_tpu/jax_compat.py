"""Version-portability shim for ``shard_map``.

The framework is written against current jax (``jax.shard_map`` with the
vma varying-axes type system).  The container this repo grows in may pin
an older release (observed: 0.4.37) where shard_map still lives in
``jax.experimental.shard_map`` and replication is tracked by the legacy
``check_rep`` pass instead of vma.  Every shard_map call site goes
through this one shim so the SPMD machinery imports and runs on both.

On the legacy path ``check_rep=False``: the old replication checker
predates the vma typing this code is written for (per-worker varying
scan carries, ``steps.anchor_invariant``) and rejects valid programs
here; on current jax the vma system supersedes it anyway.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)
